"""ZeRO weight-update sharding (parallel/zero.py), levels 1-3.

Level 1's numerics must match the replicated-update data-parallel step
exactly while optimizer state lives at 1/n per chip (arXiv:2004.13336,
PAPERS.md); levels 2 and 3 must be bit-near level 1 in params AND
per-element optax state across wire format x error feedback x
backward_passes_per_step (the uniform per-microbatch sync schedule,
docs/zero.md), with gradient shards resp. parameter shards resident at
1/n.  Plus: the level-3 shard/gather round trip (the elastic resharding
story), the EF-residual-rides-the-bucket layout, the state-layout
mismatch guard, knob validation at init, and the hvd_zero_* trace-time
observability pinned against perf/costmodel's predictions."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.parallel.data_parallel import (make_train_step, replicate,
                                                shard_batch)
from horovod_tpu.parallel import zero as Z
from horovod_tpu.parallel.zero import (init_sharded_opt_state,
                                       init_zero_state,
                                       make_zero1_train_step,
                                       make_zero_train_step,
                                       gather_zero3_params,
                                       shard_zero3_params)

def _data_mesh():
    """The legacy single-axis data mesh these tests' shard_maps hardcode
    ("hvd") — built directly from the devices, independent of the
    runtime's resolved training mesh, so the CI layout knob dimension
    (HOROVOD_LAYOUT=auto; docs/parallelism.md) keeps this suite green."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh as _Mesh
    return _Mesh(_np.array(jax.devices()), ("hvd",))


THRESH = 64  # tiny fusion threshold -> several buckets on the toy


def _model():
    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(7, 5), jnp.float32),
              "b1": jnp.asarray(rng.randn(5), jnp.float32),
              "w2": jnp.asarray(rng.randn(5, 1), jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)
    return params, loss_fn


def _batches(k, n):
    rng = np.random.RandomState(1)
    xs = rng.randn(k, 8 * n, 7).astype(np.float32)
    ys = rng.randn(k, 8 * n, 1).astype(np.float32)
    return xs, ys


def _run_chain(hvd, level, wire, ef, k, steps=2, ag_prefetch=None,
               opt=None):
    """Run `steps` optimizer steps of the bucketed chain at `level`;
    returns (final replicated params as numpy, final state)."""
    mesh = _data_mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = opt or optax.adamw(1e-2, weight_decay=0.01)
    step = make_zero_train_step(
        loss_fn, opt, mesh, zero_level=level, wire_policy=wire,
        error_feedback=ef, backward_passes_per_step=k,
        fusion_threshold_bytes=THRESH, params_template=params,
        ag_prefetch=ag_prefetch, donate=False)
    s = init_zero_state(opt, replicate(params, mesh), mesh,
                        zero_level=level, wire_policy=wire,
                        error_feedback=ef, fusion_threshold_bytes=THRESH)
    p = (shard_zero3_params(replicate(params, mesh), mesh,
                            fusion_threshold_bytes=THRESH)
         if level == 3 else replicate(params, mesh))
    rng = np.random.RandomState(1)
    for _ in range(steps):
        xs = rng.randn(k, 8 * n, 7).astype(np.float32)
        ys = rng.randn(k, 8 * n, 1).astype(np.float32)
        batch = (shard_batch(jnp.asarray(xs if k > 1 else xs[0]), mesh,
                             axis=1 if k > 1 else 0),
                 shard_batch(jnp.asarray(ys if k > 1 else ys[0]), mesh,
                             axis=1 if k > 1 else 0))
        p, s, loss = step(p, s, batch)
        assert np.isfinite(float(loss))
    if level == 3:
        p = gather_zero3_params(p, params, mesh,
                                fusion_threshold_bytes=THRESH)
    return (jax.tree_util.tree_map(np.asarray, p),
            jax.tree_util.tree_map(np.asarray, s))


def _assert_levels_agree(ref, got, tag):
    """Params bit-near AND per-element state values bit-near: the state
    layouts are identical arrays across levels (same per-bucket shard
    geometry), so the comparison is direct.  Tolerances absorb only
    compiler reassociation noise between differently-shaped programs
    (1-2 ulp observed on the EF residual)."""
    ref_p, ref_s = ref
    got_p, got_s = got
    for key in ref_p:
        np.testing.assert_allclose(got_p[key], ref_p[key], rtol=1e-5,
                                   atol=1e-6, err_msg=f"{tag} params {key}")
    ref_leaves = jax.tree_util.tree_leaves(ref_s)
    got_leaves = jax.tree_util.tree_leaves(got_s)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(b, a, rtol=1e-4, atol=2e-6,
                                   err_msg=f"{tag} state")


# ----------------------------------------------------------- level-1 legacy
def test_zero1_matches_replicated_update(hvd):
    mesh = _data_mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = optax.adamw(1e-2, weight_decay=0.01)

    ref_step = make_train_step(loss_fn, opt, mesh, axis_name="hvd")
    ref_p = replicate(params, mesh)
    ref_s = replicate(opt.init(ref_p), mesh)

    z_step = make_zero1_train_step(loss_fn, opt, mesh, axis_name="hvd")
    z_p = replicate(params, mesh)
    z_s = init_sharded_opt_state(opt, z_p, mesh, axis_name="hvd")

    xs, ys = _batches(4, n)
    for k in range(4):
        batch = (shard_batch(jnp.asarray(xs[k]), mesh),
                 shard_batch(jnp.asarray(ys[k]), mesh))
        ref_p, ref_s, ref_l = ref_step(ref_p, ref_s, batch)
        z_p, z_s, z_l = z_step(z_p, z_s, batch)
        np.testing.assert_allclose(float(ref_l), float(z_l), rtol=1e-5)
    for key in params:
        np.testing.assert_allclose(np.asarray(z_p[key]),
                                   np.asarray(ref_p[key]),
                                   rtol=2e-4, atol=2e-5)


def test_zero1_state_is_sharded(hvd):
    mesh = _data_mesh()
    n = hvd.size()
    params, _ = _model()
    opt = optax.adam(1e-3)
    state = init_sharded_opt_state(opt, replicate(params, mesh), mesh)
    total = sum(int(np.prod(l.shape)) for l in
                jax.tree_util.tree_leaves(params))
    padded = -(-total // n) * n
    mu = state[0].mu  # ScaleByAdamState
    assert mu.shape == (n, padded // n)
    # each chip holds exactly one shard row
    for shard in mu.addressable_shards:
        assert shard.data.shape == (1, padded // n)


def test_zero1_rejects_non_average(hvd):
    from horovod_tpu.common.reduce_op import Sum
    params, loss_fn = _model()
    with pytest.raises(ValueError, match="Average"):
        make_zero1_train_step(loss_fn, optax.sgd(0.1), _data_mesh(), op=Sum)


def test_zero1_loss_decreases(hvd):
    mesh = _data_mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = optax.sgd(0.05, momentum=0.9)
    step = make_zero1_train_step(loss_fn, opt, mesh)
    p = replicate(params, mesh)
    s = init_sharded_opt_state(opt, p, mesh)
    xs, ys = _batches(1, n)
    batch = (shard_batch(jnp.asarray(xs[0]), mesh),
             shard_batch(jnp.asarray(ys[0]), mesh))
    losses = []
    for _ in range(15):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses


# ----------------------------------------------------- level equivalence
def test_zero_levels_equivalent_core(hvd):
    """The fast-tier slice of the acceptance matrix: levels 2 and 3
    agree with level 1 in params and per-element optax state — one
    lossless, one cast + EF, one quantized config (the full wire x EF x
    k product runs in test_zero_levels_equivalent_matrix)."""
    for wire, ef, k in (("none", False, 2), ("bf16", True, 2),
                        ("int8_ring", True, 1)):
        ref = _run_chain(hvd, 1, wire, ef, k)
        for level in (2, 3):
            _assert_levels_agree(ref, _run_chain(hvd, level, wire, ef, k),
                                 f"wire={wire} ef={ef} k={k} lvl{level}")


def test_zero_levels_equivalent_matrix(hvd):
    """The full acceptance matrix (slow tier): level 1/2/3 params AND
    per-element optax state agree across wire format {none, bf16,
    int8_ring} x EF {off, on} x backward_passes_per_step {1, 2, 4}."""
    for wire in ("none", "bf16", "int8_ring"):
        for ef in (False, True):
            for k in (1, 2, 4):
                ref = _run_chain(hvd, 1, wire, ef, k)
                for level in (2, 3):
                    _assert_levels_agree(
                        ref, _run_chain(hvd, level, wire, ef, k),
                        f"wire={wire} ef={ef} k={k} lvl{level}")


def test_zero_interleaved_level1_matches_monolithic_anchor(hvd):
    """The bucketed chain's anchor: level 1 interleaved (k=1, lossless)
    lands the same params as the legacy monolithic flat-vector chain."""
    mesh = _data_mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = optax.adam(1e-2)
    mono = make_zero1_train_step(loss_fn, opt, mesh, donate=False)
    m_p = replicate(params, mesh)
    m_s = init_sharded_opt_state(opt, m_p, mesh)
    xs, ys = _batches(3, n)
    for t in range(3):
        batch = (shard_batch(jnp.asarray(xs[t]), mesh),
                 shard_batch(jnp.asarray(ys[t]), mesh))
        m_p, m_s, _ = mono(m_p, m_s, batch)
    step = make_zero_train_step(loss_fn, opt, mesh, zero_level=1,
                                wire_policy="none",
                                fusion_threshold_bytes=THRESH,
                                donate=False)
    i_p = replicate(params, mesh)
    i_s = init_zero_state(opt, i_p, mesh, zero_level=1,
                          wire_policy="none",
                          fusion_threshold_bytes=THRESH)
    for t in range(3):
        batch = (shard_batch(jnp.asarray(xs[t]), mesh),
                 shard_batch(jnp.asarray(ys[t]), mesh))
        i_p, i_s, _ = step(i_p, i_s, batch)
    for key in params:
        np.testing.assert_allclose(np.asarray(i_p[key]),
                                   np.asarray(m_p[key]),
                                   rtol=1e-6, atol=1e-7)


def test_zero_ag_prefetch_is_scheduling_only(hvd):
    """HOROVOD_ZERO_AG_PREFETCH moves the level-3 param gathers'
    program position, never the values: depths 1 and 4 land identical
    params."""
    p1, _ = _run_chain(hvd, 3, "none", False, 2, ag_prefetch=1)
    p4, _ = _run_chain(hvd, 3, "none", False, 2, ag_prefetch=4)
    for key in p1:
        np.testing.assert_allclose(p4[key], p1[key], rtol=1e-6,
                                   atol=1e-7)


# ----------------------------------------------------- level-3 param story
def test_zero3_shard_gather_roundtrip_and_shapes(hvd):
    mesh = _data_mesh()
    n = hvd.size()
    params, _ = _model()
    from horovod_tpu.parallel.zero import _bucket_plan
    plan = _bucket_plan(params, THRESH)
    shards = shard_zero3_params(replicate(params, mesh), mesh,
                                fusion_threshold_bytes=THRESH)
    assert len(shards) == plan.num_buckets
    for bi, b in enumerate(plan.buckets):
        padded = -(-sum(b.sizes) // n) * n
        assert shards[bi].shape == (n, padded // n)
        # each chip holds exactly its 1/n row
        for sh in shards[bi].addressable_shards:
            assert sh.data.shape == (1, padded // n)
    back = gather_zero3_params(shards, params, mesh,
                               fusion_threshold_bytes=THRESH)
    for key in params:
        np.testing.assert_array_equal(np.asarray(back[key]),
                                      np.asarray(params[key]))


def test_zero3_geometry_rederives_for_new_world_size(hvd):
    """The elastic/chaos reset contract (docs/zero.md): shard geometry
    is a pure function of (plan, world size) — gather at the old mesh,
    re-shard at a DIFFERENT world size, values survive bit-exact."""
    from jax.sharding import Mesh
    mesh = _data_mesh()
    params, _ = _model()
    small = Mesh(np.array(jax.devices()[:2]), ("hvd",))
    big_shards = shard_zero3_params(replicate(params, mesh), mesh,
                                    fusion_threshold_bytes=THRESH)
    full = gather_zero3_params(big_shards, params, mesh,
                               fusion_threshold_bytes=THRESH)
    small_shards = shard_zero3_params(replicate(params, small), small,
                                      fusion_threshold_bytes=THRESH)
    # different world size -> different shard geometry, same values
    assert big_shards[0].shape[0] == hvd.size()
    assert small_shards[0].shape[0] == 2
    back = gather_zero3_params(small_shards, params, small,
                               fusion_threshold_bytes=THRESH)
    for key in params:
        np.testing.assert_array_equal(np.asarray(back[key]),
                                      np.asarray(full[key]))


def test_zero_ef_residual_sharded_with_buckets(hvd):
    """EF residuals ride the per-bucket sharded state: one rank-local
    [n, bucket] row block per bucket (docs/zero.md#wire-composition),
    nonzero after lossy syncs."""
    from horovod_tpu.parallel.zero import _ZeroEFBlock, _bucket_plan
    mesh = _data_mesh()
    n = hvd.size()
    params, _ = _model()
    plan = _bucket_plan(params, THRESH)
    opt = optax.sgd(0.05)
    state = init_zero_state(opt, replicate(params, mesh), mesh,
                            zero_level=2, wire_policy="int8_ring",
                            error_feedback=True,
                            fusion_threshold_bytes=THRESH)
    assert len(state) == plan.num_buckets
    for bi, b in enumerate(plan.buckets):
        assert isinstance(state[bi], _ZeroEFBlock)
        padded = -(-sum(b.sizes) // n) * n
        assert state[bi].residual.shape == (n, padded)
        for sh in state[bi].residual.addressable_shards:
            assert sh.data.shape == (1, padded)
    _, final = _run_chain(hvd, 2, "int8_ring", True, 2,
                          opt=optax.sgd(0.05))
    norms = [float(np.abs(final[bi].residual).sum())
             for bi in range(plan.num_buckets)]
    assert any(v > 0 for v in norms), norms
    # EF off (or lossless wire): plain per-bucket optax blocks
    plain = init_zero_state(opt, replicate(params, mesh), mesh,
                            zero_level=2, wire_policy="none",
                            error_feedback=True,
                            fusion_threshold_bytes=THRESH)
    assert not isinstance(plain[0], _ZeroEFBlock)


# ------------------------------------------------- layout/validation guards
def test_zero_mismatched_state_layout_raises(hvd):
    """The satellite fix: state inited interleaved=True consumed by a
    monolithic step builder must RAISE, not mis-slice — and the
    converse."""
    mesh = _data_mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = optax.adam(1e-2)
    xs, ys = _batches(1, n)
    batch = (shard_batch(jnp.asarray(xs[0]), mesh),
             shard_batch(jnp.asarray(ys[0]), mesh))
    p = replicate(params, mesh)

    mono_step = make_zero1_train_step(loss_fn, opt, mesh, donate=False)
    inter_state = init_sharded_opt_state(opt, p, mesh, interleaved=True,
                                         fusion_threshold_bytes=THRESH)
    with pytest.raises(ValueError, match="interleaved"):
        mono_step(p, inter_state, batch)

    inter_step = make_zero_train_step(loss_fn, opt, mesh, zero_level=1,
                                      fusion_threshold_bytes=THRESH,
                                      donate=False)
    mono_state = init_sharded_opt_state(opt, p, mesh)
    with pytest.raises(ValueError, match="layout mismatch"):
        inter_step(p, mono_state, batch)


def test_zero_builder_argument_validation(hvd):
    mesh = _data_mesh()
    params, loss_fn = _model()
    opt = optax.sgd(0.1)
    with pytest.raises(ValueError, match="zero_level=0"):
        make_zero_train_step(loss_fn, opt, mesh, zero_level=0)
    with pytest.raises(ValueError, match="plain data parallelism"):
        init_zero_state(opt, params, mesh, zero_level=0)
    with pytest.raises(ValueError, match="bucket-interleaved"):
        make_zero_train_step(loss_fn, opt, mesh, zero_level=2,
                             interleaved=False)
    with pytest.raises(ValueError, match="bucket-interleaved|interleaved"):
        init_sharded_opt_state(opt, params, mesh, zero_level=3,
                               interleaved=False)
    with pytest.raises(ValueError, match="params_template"):
        make_zero_train_step(loss_fn, opt, mesh, zero_level=3)
    with pytest.raises(ValueError, match="monolithic"):
        make_zero_train_step(loss_fn, opt, mesh, zero_level=1,
                             interleaved=False,
                             backward_passes_per_step=2)
    with pytest.raises(ValueError, match="wire"):
        make_zero_train_step(loss_fn, opt, mesh, zero_level=1,
                             interleaved=False, wire_policy="int8_ring")
    with pytest.raises(ValueError, match="zero level"):
        make_zero_train_step(loss_fn, opt, mesh, zero_level=7)


@pytest.mark.parametrize("knob,bad", [
    ("HOROVOD_ZERO_LEVEL", "5"),
    ("HOROVOD_ZERO_LEVEL", "-1"),
    ("HOROVOD_ZERO_AG_PREFETCH", "0"),
    ("HOROVOD_ZERO_AG_PREFETCH", "99"),
])
def test_zero_knobs_fail_loudly_at_init(hvd, monkeypatch, knob, bad):
    """The knob satellite: HOROVOD_ZERO_LEVEL / HOROVOD_ZERO_AG_PREFETCH
    are validated at hvd.init with the knob named."""
    import horovod_tpu as h
    monkeypatch.setenv(knob, bad)
    h.shutdown()
    try:
        with pytest.raises(ValueError, match=knob):
            h.init()
    finally:
        monkeypatch.delenv(knob)
        h.init()


def test_zero_resolution_order(hvd, monkeypatch):
    """kwarg > knob for the level; kwarg > tuned bandit arm > knob for
    the AG prefetch depth (the overlap-depth arm covers it)."""
    import os

    import horovod_tpu.runtime as hrt
    from horovod_tpu.parallel.zero import (resolve_ag_prefetch,
                                           resolve_zero_level)

    # knob-driven default (CI's zero-3 dimension flips the env)
    base = int(os.environ.get("HOROVOD_ZERO_LEVEL", "") or 1)
    assert resolve_zero_level() == base
    assert resolve_zero_level(3) == 3         # kwarg wins
    monkeypatch.setenv("HOROVOD_ZERO_LEVEL", "2")
    assert resolve_zero_level() == 2          # env-live
    assert resolve_zero_level(1) == 1

    rt = hrt.get()
    pre = int(os.environ.get("HOROVOD_ZERO_AG_PREFETCH", "") or 2)
    assert rt.zero_ag_prefetch() == pre       # knob-driven
    monkeypatch.setenv("HOROVOD_ZERO_AG_PREFETCH", "4")
    assert resolve_ag_prefetch() == 4
    assert resolve_ag_prefetch(1) == 1        # kwarg wins
    monkeypatch.delenv("HOROVOD_ZERO_AG_PREFETCH")

    class _Tuner:
        overlap_depth = 3
    monkeypatch.setattr(rt, "autotuner", _Tuner())
    assert rt.zero_ag_prefetch() == 3         # bandit arm refines
    assert resolve_ag_prefetch() == 3


# ------------------------------------------------------- observability pins
def test_zero_metrics_and_costmodel_pin(hvd):
    """After a level-3 trace: the hvd_zero_* gauges carry level /
    prefetch / per-kind sharded bytes, the overlap gauges carry the
    plane=zero3 split, and the trace-time byte model EQUALS
    perf/costmodel.zero_comm_bytes' prediction (the model-closure
    contract of docs/zero.md)."""
    import horovod_tpu as h
    from horovod_tpu.ops.overlap import priority_order
    from horovod_tpu.parallel.zero import _bucket_plan
    from horovod_tpu.perf import costmodel as cm
    from horovod_tpu.utils import metrics as M

    n = hvd.size()
    k = 2
    _run_chain(hvd, 3, "none", False, k, steps=1)
    assert M.ZERO_LEVEL.value() == 3
    assert M.ZERO_AG_PREFETCH.value() == Z.resolve_ag_prefetch()
    params, _ = _model()
    plan = _bucket_plan(params, THRESH)
    order = priority_order(plan)
    padded = [-(-sum(b.sizes) // n) * n for b in plan.buckets]
    per_bucket = [cm.zero_comm_bytes(L, n, 3, k=k)["total_bytes"]
                  for L in padded]
    expected_exposed = 0.5 * (per_bucket[order[0]] + per_bucket[order[-1]])
    got_exposed = M.OVERLAP_EXPOSED_BYTES.value(plane="zero3")
    assert got_exposed == pytest.approx(expected_exposed)
    frac = M.OVERLAP_FRACTION.value(plane="zero3")
    assert frac == pytest.approx(1.0 - expected_exposed / sum(per_bucket))

    elems = sum(padded)
    assert M.ZERO_SHARDED_BYTES.value(kind="grads") == elems * 4 // n
    assert M.ZERO_SHARDED_BYTES.value(kind="ef_residual") == 0
    pbytes = sum(int(np.prod(l.shape)) * 4
                 for l in jax.tree_util.tree_leaves(params))
    assert M.ZERO_SHARDED_BYTES.value(kind="params") == pbytes // n
    assert M.ZERO_SHARDED_BYTES.value(kind="opt_state") > 0

    fams = h.metrics_snapshot()["families"]
    for fam in ("hvd_zero_level", "hvd_zero_sharded_bytes",
                "hvd_zero_ag_prefetch_depth"):
        assert fam in fams, fam

    # level 2 k>1 moves strictly fewer bytes than level 1 (the
    # ZeRO-2 wire claim); equal at k=1
    l1 = cm.zero_comm_bytes(1000, n, 1, k=4)["total_bytes"]
    l2 = cm.zero_comm_bytes(1000, n, 2, k=4)["total_bytes"]
    assert l2 < l1
    assert (cm.zero_comm_bytes(1000, n, 1)["total_bytes"]
            == cm.zero_comm_bytes(1000, n, 2)["total_bytes"]
            == cm.zero_comm_bytes(1000, n, 0)["total_bytes"])


def test_zero_trace_markers_in_timeline(hvd, tmp_path):
    """The merged-timeline satellite: a level-3 trace leaves
    zero.bucket.{ag,rs,free} instants (docs/timeline.md)."""
    import horovod_tpu as h
    from horovod_tpu.utils.timeline import load_trace_events

    path = str(tmp_path / "zero_trace.json")
    h.start_timeline(path)
    try:
        _run_chain(hvd, 3, "none", False, 1, steps=1)
    finally:
        h.stop_timeline()
    names = {e.get("name") for e in load_trace_events(path)}
    for marker in ("zero.bucket.ag", "zero.bucket.rs",
                   "zero.bucket.free"):
        assert marker in names, (marker, sorted(names))
