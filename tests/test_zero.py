"""ZeRO-1 weight-update sharding (parallel/zero.py): numerics must match
the replicated-update data-parallel step exactly while the optimizer
state lives at 1/n per chip (arXiv:2004.13336, PAPERS.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.parallel.data_parallel import (make_train_step, replicate,
                                                shard_batch)
from horovod_tpu.parallel.zero import (init_sharded_opt_state,
                                       make_zero1_train_step)


def _model():
    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(7, 5), jnp.float32),
              "b1": jnp.asarray(rng.randn(5), jnp.float32),
              "w2": jnp.asarray(rng.randn(5, 1), jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)
    return params, loss_fn


def _batches(k, n):
    rng = np.random.RandomState(1)
    xs = rng.randn(k, 8 * n, 7).astype(np.float32)
    ys = rng.randn(k, 8 * n, 1).astype(np.float32)
    return xs, ys


def test_zero1_matches_replicated_update(hvd):
    mesh = hvd.mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = optax.adamw(1e-2, weight_decay=0.01)

    ref_step = make_train_step(loss_fn, opt, mesh, axis_name="hvd")
    ref_p = replicate(params, mesh)
    ref_s = replicate(opt.init(ref_p), mesh)

    z_step = make_zero1_train_step(loss_fn, opt, mesh, axis_name="hvd")
    z_p = replicate(params, mesh)
    z_s = init_sharded_opt_state(opt, z_p, mesh, axis_name="hvd")

    xs, ys = _batches(4, n)
    for k in range(4):
        batch = (shard_batch(jnp.asarray(xs[k]), mesh),
                 shard_batch(jnp.asarray(ys[k]), mesh))
        ref_p, ref_s, ref_l = ref_step(ref_p, ref_s, batch)
        z_p, z_s, z_l = z_step(z_p, z_s, batch)
        np.testing.assert_allclose(float(ref_l), float(z_l), rtol=1e-5)
    for key in params:
        np.testing.assert_allclose(np.asarray(z_p[key]),
                                   np.asarray(ref_p[key]),
                                   rtol=2e-4, atol=2e-5)


def test_zero1_state_is_sharded(hvd):
    mesh = hvd.mesh()
    n = hvd.size()
    params, _ = _model()
    opt = optax.adam(1e-3)
    state = init_sharded_opt_state(opt, replicate(params, mesh), mesh)
    total = sum(int(np.prod(l.shape)) for l in
                jax.tree_util.tree_leaves(params))
    padded = -(-total // n) * n
    mu = state[0].mu  # ScaleByAdamState
    assert mu.shape == (n, padded // n)
    # each chip holds exactly one shard row
    for shard in mu.addressable_shards:
        assert shard.data.shape == (1, padded // n)


def test_zero1_rejects_non_average(hvd):
    from horovod_tpu.common.reduce_op import Sum
    params, loss_fn = _model()
    with pytest.raises(ValueError, match="Average"):
        make_zero1_train_step(loss_fn, optax.sgd(0.1), hvd.mesh(), op=Sum)


def test_zero1_loss_decreases(hvd):
    mesh = hvd.mesh()
    n = hvd.size()
    params, loss_fn = _model()
    opt = optax.sgd(0.05, momentum=0.9)
    step = make_zero1_train_step(loss_fn, opt, mesh)
    p = replicate(params, mesh)
    s = init_sharded_opt_state(opt, p, mesh)
    xs, ys = _batches(1, n)
    batch = (shard_batch(jnp.asarray(xs[0]), mesh),
             shard_batch(jnp.asarray(ys[0]), mesh))
    losses = []
    for _ in range(15):
        p, s, loss = step(p, s, batch)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], losses
