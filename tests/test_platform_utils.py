"""Platform binding utilities (utils/platform.py): the deterministic-CPU
contract every smoke path depends on (round-3 judged failure: a spawned
subprocess hung 900 s because the env var alone loses to site-customized
jax config)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          env=env)


def test_force_cpu_binds_config_and_strips_trigger():
    out = _run("""
import os
os.environ["PALLAS_AXON_POOL_IPS"] = "198.51.100.1"  # pretend-armed
from horovod_tpu.utils.platform import force_cpu
force_cpu(virtual_chips=4)
import jax
assert jax.config.jax_platforms == "cpu"
assert os.environ["JAX_PLATFORMS"] == "cpu"
assert "PALLAS_AXON_POOL_IPS" not in os.environ  # children protected
assert "xla_force_host_platform_device_count=4" in os.environ["XLA_FLAGS"]
assert len(jax.devices()) == 4
print("OK")
""", env_extra={"XLA_FLAGS": "", "JAX_PLATFORMS": ""})
    assert out.returncode == 0 and "OK" in out.stdout, out.stderr[-800:]


def test_force_cpu_respects_existing_device_count():
    out = _run("""
from horovod_tpu.utils.platform import force_cpu
force_cpu(virtual_chips=4)  # launcher already set 2; must NOT clobber
import os
assert "device_count=2" in os.environ["XLA_FLAGS"], os.environ["XLA_FLAGS"]
import jax
assert len(jax.devices()) == 2
print("OK")
""", env_extra={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    assert out.returncode == 0 and "OK" in out.stdout, out.stderr[-800:]


def test_apply_env_platform_noop_without_env():
    out = _run("""
import importlib.util, os, sys
os.environ.pop("JAX_PLATFORMS", None)
# load the MODULE by path: importing the horovod_tpu package would pull
# jax in via unrelated subpackages and mask the contract under test
spec = importlib.util.spec_from_file_location(
    "platform_mod", os.path.join(%r, "horovod_tpu", "utils", "platform.py"))
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.apply_env_platform()  # no env: must not touch jax at all
assert "jax" not in sys.modules, "apply_env_platform imported jax"
print("OK")
""" % REPO, env_extra={"JAX_PLATFORMS": ""})
    assert out.returncode == 0 and "OK" in out.stdout, out.stderr[-800:]


def test_force_cpu_raises_after_foreign_backend_init():
    # Simulate "called too late": initialize the cpu backend under a
    # DIFFERENT platform string first, then force_cpu must raise rather
    # than silently mis-bind.  (cpu-only image: we emulate by
    # initializing, then asking for an impossible switch.)
    out = _run("""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.devices()  # initialize backends
from horovod_tpu.utils import platform as P
# monkeypatch the observed config so the switch path runs post-init
class FakeCfg:
    jax_platforms = "tpu"
    @staticmethod
    def update(k, v):
        raise RuntimeError("backends already initialized")
jax.config = FakeCfg()
try:
    P.force_cpu()
    print("NO-RAISE")
except RuntimeError as e:
    assert "before any jax-touching import" in str(e)
    print("OK")
""")
    assert out.returncode == 0 and "OK" in out.stdout, \
        out.stdout + out.stderr[-500:]
