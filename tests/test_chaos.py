"""Fast tier for the chaos plane (no subprocess fan-out; the
multi-process proofs live in tests/integration/test_chaos_integration.py):

  * spec parsing — YAML/JSON, both event spellings, validation errors,
    transport->env mapping;
  * schedule determinism — fixed seed => identical per-rank decision
    streams, different ranks => independent streams (the same
    golden-ratio mix csrc/transport.cc applies);
  * reconnect/backoff sequencing — the shared exponential+jitter
    schedule both the KV client and the native transport follow;
  * KV writer retry — put_kv rides out transient refusals and injected
    blackouts, surfaces non-transient errors immediately;
  * injector event semantics — kill/stall/crash_commit firing, one-shot
    state_dir memory across incarnations;
  * hvd_core_metrics round-trip — the native fault/retry counters come
    back through the versioned metrics block, zero on a clean loopback
    core and nonzero across a real chaos-injected TCP reconnect.
"""

import multiprocessing
import os
import random
import time
import urllib.error

import pytest

from horovod_tpu import chaos
from horovod_tpu.chaos.injector import ChaosInjector, rank_stream_seed
from horovod_tpu.common.util import backoff_delays


# ----------------------------------------------------------------- spec
def test_spec_yaml_both_event_spellings(tmp_path):
    p = tmp_path / "spec.yaml"
    p.write_text("""
seed: 42
state_dir: /tmp/x
transport:
  close_after: 5
  rank: 1
events:
  - kill: {rank: 1, step: 2, exit_code: 3}
  - {kind: stall, rank: 0, point: complete, duration_ms: 25}
""")
    spec = chaos.load_spec(str(p))
    assert spec.seed == 42 and spec.state_dir == "/tmp/x"
    assert [e.kind for e in spec.events] == ["kill", "stall"]
    assert spec.events[0].rank == 1 and spec.events[0].exit_code == 3
    assert spec.events[1].point == "complete"
    env = spec.transport_env()
    assert env["HOROVOD_CHAOS_TCP_CLOSE_AFTER"] == "5"
    assert env["HOROVOD_CHAOS_TCP_RANK"] == "1"
    assert env["HOROVOD_CHAOS_SEED"] == "42"
    # every exported env var is a registered knob (the pipeline golden
    # test enforces the same property on CI steps)
    from horovod_tpu.common.knobs import KNOBS
    assert set(env) <= set(KNOBS)


def test_spec_json_roundtrip():
    spec = chaos.parse_spec({
        "seed": 9, "transport": {"dup_rate": 0.5},
        "events": [{"kind": "kv_blackout", "op": "put", "count": 2}]})
    again = chaos.loads_spec(spec.to_json())
    assert again.events == spec.events
    assert again.transport == spec.transport and again.seed == spec.seed


@pytest.mark.parametrize("doc,msg", [
    ({"events": [{"kind": "explode"}]}, "kind"),
    ({"events": [{"kill": {"rank": 0}, "stall": {}}]}, "kind"),
    ({"transport": {"nuke_rate": 1.0}}, "transport"),
    ({"events": [{"kind": "kill", "blast_radius": 2}]}, "unknown fields"),
    ({"chaos": True}, "top-level"),
    # field-level type errors name the EVENT INDEX and the FIELD
    ({"events": [{"kind": "kill", "rank": "one"}]},
     r"event #0 \(kill\) field 'rank': expected int, got 'one' \(str\)"),
    ({"events": [{"kind": "kill"},
                 {"stall": {"duration_ms": "long"}}]},
     r"event #1 \(stall\) field 'duration_ms': expected int/float"),
    ({"events": [{"kv_blackout": {"op": 3}}]},
     r"event #0 \(kv_blackout\) field 'op': expected str, got 3"),
    # YAML's `rank: true` is a typo, not an int
    ({"events": [{"kind": "kill", "rank": True}]},
     r"event #0 \(kill\) field 'rank': expected int, got True \(bool\)"),
    ({"events": [{"kill": "rank 1"}]},
     r"event #0 \(kill\) body must be a mapping, got 'rank 1'"),
])
def test_spec_validation_fails_loudly(doc, msg):
    with pytest.raises(ValueError, match=msg):
        chaos.parse_spec(doc)


def test_merge_specs_concatenates_and_defers():
    """--chaos + scenario storm compose: events concatenate base-first,
    unset scalars defer to whichever side set them."""
    base = chaos.parse_spec({"seed": 9, "events": [
        {"kind": "stall", "rank": 0, "step": 1}]})
    extra = chaos.parse_spec({
        "state_dir": "/tmp/st", "transport": {"dup_rate": 0.25},
        "events": [{"kill": {"rank": 1, "step": 5}}]})
    merged = chaos.merge_specs(base, extra)
    assert [e.kind for e in merged.events] == ["stall", "kill"]
    assert merged.seed == 9 and merged.state_dir == "/tmp/st"
    assert merged.transport == {"dup_rate": 0.25}
    # agreement is not a conflict
    same = chaos.merge_specs(base, chaos.parse_spec({"seed": 9}))
    assert same.seed == 9


@pytest.mark.parametrize("base,extra,msg", [
    ({"seed": 9}, {"seed": 10},
     r"seed conflicts between --chaos \(9\) and scenario storm \(10\)"),
    ({"state_dir": "/a"}, {"state_dir": "/b"},
     r"state_dir conflicts"),
    ({"transport": {"dup_rate": 0.1}}, {"transport": {"dup_rate": 0.2}},
     r"transport fault 'dup_rate' conflicts"),
])
def test_merge_specs_refuses_contradictions(base, extra, msg):
    with pytest.raises(ValueError, match=msg):
        chaos.merge_specs(chaos.parse_spec(base), chaos.parse_spec(extra))


def test_ensure_installed_from_spec_file(tmp_path, monkeypatch):
    p = tmp_path / "spec.yaml"
    p.write_text("seed: 5\nevents:\n  - stall: {duration_ms: 1}\n")
    monkeypatch.setenv("HOROVOD_CHAOS_SPEC", str(p))
    monkeypatch.setenv("HOROVOD_RANK", "3")
    chaos.uninstall()
    try:
        inj = chaos.ensure_installed()
        assert inj is not None and inj.rank == 3
        assert inj.spec.seed == 5
        assert chaos.active() is inj
    finally:
        chaos.uninstall()


def test_ensure_installed_from_rendezvous_kv(monkeypatch):
    from horovod_tpu.runner.http_server import RendezvousServer
    spec = chaos.parse_spec({"seed": 21, "events": [
        {"kind": "stall", "rank": 0, "point": "x", "duration_ms": 1}]})
    server = RendezvousServer()
    port = server.start()
    server.put(chaos.KV_SCOPE, chaos.KV_KEY, spec.to_json().encode())
    monkeypatch.setenv("HOROVOD_CHAOS", "1")
    monkeypatch.setenv("HOROVOD_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_RENDEZVOUS_PORT", str(port))
    monkeypatch.setenv("HOROVOD_RANK", "1")
    chaos.uninstall()
    try:
        inj = chaos.ensure_installed()
        assert inj is not None and inj.spec.seed == 21 and inj.rank == 1
    finally:
        chaos.uninstall()
        server.stop()


# ----------------------------------------------------------- determinism
def test_rank_streams_deterministic_and_independent():
    spec = chaos.parse_spec({"seed": 1234})
    a1 = ChaosInjector(spec, rank=0).rng
    a2 = ChaosInjector(spec, rank=0).rng
    b = ChaosInjector(spec, rank=1).rng
    seq_a1 = [a1.random() for _ in range(32)]
    seq_a2 = [a2.random() for _ in range(32)]
    seq_b = [b.random() for _ in range(32)]
    assert seq_a1 == seq_a2          # same seed+rank => same schedule
    assert seq_a1 != seq_b           # ranks draw independent streams
    # the mix matches what csrc/transport.cc applies to HOROVOD_CHAOS_SEED
    assert rank_stream_seed(1234, 0) == \
        (1234 ^ (0x9E3779B97F4A7C15 * 1)) & 0xFFFFFFFFFFFFFFFF


# -------------------------------------------------------------- backoff
def test_backoff_schedule_sequencing():
    rng = random.Random(7)
    delays = backoff_delays(6, base_ms=50, cap_ms=2000, rng=rng)
    assert len(delays) == 6
    step = 50.0
    for d in delays:
        capped = min(step, 2000.0)
        assert capped / 2000.0 <= d <= capped / 1000.0  # U[step/2, step]
        step *= 2
    # deterministic under a fixed rng seed
    assert delays == backoff_delays(6, 50, 2000, rng=random.Random(7))
    assert backoff_delays(0, 50) == []


# ------------------------------------------------------------- KV retry
def _flaky_urlopen(failures, exc=None):
    calls = {"n": 0}

    def fake(req, timeout=None):
        calls["n"] += 1
        if calls["n"] <= failures:
            raise exc or urllib.error.URLError("connection refused")

        class Resp:
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

            def read(self):
                return b"ok"
        return Resp()
    return fake, calls


def test_put_kv_retries_transient_refusal(monkeypatch):
    import horovod_tpu.runner.http_client as hc
    fake, calls = _flaky_urlopen(2)
    monkeypatch.setattr(hc.urllib.request, "urlopen", fake)
    monkeypatch.setattr(hc.time, "sleep", lambda s: None)
    hc.put_kv("127.0.0.1", 1, "s", "k", b"v", retries=3)
    assert calls["n"] == 3  # 2 failures + 1 success


def test_put_kv_budget_exhaustion_raises(monkeypatch):
    import horovod_tpu.runner.http_client as hc
    fake, calls = _flaky_urlopen(99)
    monkeypatch.setattr(hc.urllib.request, "urlopen", fake)
    monkeypatch.setattr(hc.time, "sleep", lambda s: None)
    with pytest.raises(urllib.error.URLError):
        hc.put_kv("127.0.0.1", 1, "s", "k", b"v", retries=2)
    assert calls["n"] == 3  # initial + 2 retries, then surface


def test_put_kv_client_error_not_retried(monkeypatch):
    import horovod_tpu.runner.http_client as hc
    fake, calls = _flaky_urlopen(
        99, exc=urllib.error.HTTPError("u", 403, "forbidden", {}, None))
    monkeypatch.setattr(hc.urllib.request, "urlopen", fake)
    with pytest.raises(urllib.error.HTTPError):
        hc.put_kv("127.0.0.1", 1, "s", "k", b"v", retries=5)
    assert calls["n"] == 1  # a 4xx is a caller bug: no retry


def test_put_kv_rides_out_injected_blackout():
    """Blackout (2 ops) < retry budget (3): the writer must survive —
    the interaction the chaos plane exists to prove."""
    from horovod_tpu.runner.http_client import get_kv, put_kv
    from horovod_tpu.runner.http_server import RendezvousServer
    spec = chaos.parse_spec({"events": [
        {"kind": "kv_blackout", "op": "put", "count": 2}]})
    server = RendezvousServer()
    port = server.start()
    chaos.install(spec, rank=0)
    try:
        put_kv("127.0.0.1", port, "s", "k", b"v", retries=3)
        assert get_kv("127.0.0.1", port, "s", "k", timeout=2) == b"v"
    finally:
        chaos.uninstall()
        server.stop()


# ------------------------------------------------------ injector events
def _raise_exit(code):
    raise SystemExit(code)


def test_kill_fires_at_step_for_matching_rank():
    spec = chaos.parse_spec({"events": [
        {"kind": "kill", "rank": 1, "step": 2, "exit_code": 9}]})
    inj = ChaosInjector(spec, rank=1, exit_fn=_raise_exit)
    inj.on_step(0)
    inj.on_step(1)
    with pytest.raises(SystemExit) as e:
        inj.on_step(2)
    assert e.value.code == 9
    ChaosInjector(spec, rank=0, exit_fn=_raise_exit).on_step(2)  # no-op


def test_stall_points_and_step_stalls():
    spec = chaos.parse_spec({"events": [
        {"kind": "stall", "rank": 0, "point": "negotiate",
         "duration_ms": 70},
        {"kind": "stall", "rank": 0, "step": 4, "duration_ms": 30}]})
    slept = []
    inj = ChaosInjector(spec, rank=0, sleep_fn=slept.append)
    inj.maybe_stall("negotiate")
    inj.maybe_stall("other")       # point mismatch: nothing
    inj.on_step(3)                 # step mismatch: nothing
    inj.on_step(4)
    assert slept == [0.07, 0.03]
    ChaosInjector(spec, rank=1, sleep_fn=slept.append).maybe_stall(
        "negotiate")               # rank mismatch: nothing
    assert slept == [0.07, 0.03]


def test_crash_commit_one_shot_across_incarnations(tmp_path):
    spec = chaos.parse_spec({
        "state_dir": str(tmp_path),
        "events": [{"kind": "crash_commit", "rank": 0, "step": 3}]})
    inj = ChaosInjector(spec, rank=0, exit_fn=_raise_exit)
    inj.crash_point("fastcommit.pre_marker", 2)   # wrong step: no fire
    inj.crash_point("fastcommit.pre_manifest", 3)  # wrong point: no fire
    with pytest.raises(SystemExit):
        inj.crash_point("fastcommit.pre_marker", 3)
    # the restarted incarnation sees the fired marker and must NOT crash
    again = ChaosInjector(spec, rank=0, exit_fn=_raise_exit)
    again.crash_point("fastcommit.pre_marker", 3)


# --------------------------------------------- native counter round-trip
def test_loopback_core_metrics_carry_fault_counters():
    """A clean loopback core reports the transport/chaos counters as
    present-and-zero — absence would mean the name-keyed metrics contract
    lost the families, zero means no phantom faults."""
    from horovod_tpu.common.basics import CoordinationCore, LoopbackHub
    hub = LoopbackHub(1)
    core = CoordinationCore.loopback(hub, 0, cycle_ms=0.2)
    try:
        c = core.metrics()["counters"]
        for key in ("transport_reconnects", "transport_reconnect_failures",
                    "transport_frames_resent", "transport_frames_dropped",
                    "chaos_faults_injected"):
            assert c.get(key) == 0, (key, c)
    finally:
        core.shutdown()
        core.close()
        hub.close()


def _tcp_chaos_worker(rank, port, results):
    from horovod_tpu.common.basics import CoordinationCore, OP_ALLREDUCE
    core = CoordinationCore.tcp(rank, 2, "127.0.0.1", port, cycle_ms=0.5)
    for i in range(10):
        core.submit(f"t{i}", "f32:8:sum", OP_ALLREDUCE, 32)
        r = core.wait(20.0)
        assert r is not None and r.type == "OK", (rank, i, r)
    c = core.metrics()["counters"]
    results[rank] = {k: v for k, v in c.items()
                     if k.startswith(("transport_", "chaos_"))}
    core.shutdown()
    time.sleep(0.3)
    core.close()


def test_tcp_fault_counters_roundtrip_through_core_metrics():
    """Two real processes, an injected disconnect on rank 1: negotiation
    completes via reconnect and BOTH ranks' hvd_core_metrics blocks carry
    the recovery (reconnects/resends on the worker, re-accept on rank 0)."""
    env = {"HOROVOD_CHAOS_TCP_CLOSE_AFTER": "4",
           "HOROVOD_CHAOS_TCP_RANK": "1",
           "HOROVOD_CHAOS_SEED": "3",
           "HOROVOD_CONTROLLER_RETRY_BACKOFF_MS": "20"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        ctx = multiprocessing.get_context("spawn")
        mgr = ctx.Manager()
        results = mgr.dict()
        procs = [ctx.Process(target=_tcp_chaos_worker,
                             args=(r, 29521, results)) for r in range(2)]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=60)
            assert not p.is_alive(), "tcp chaos worker hung"
            assert p.exitcode == 0
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    assert results[1]["chaos_faults_injected"] >= 1, dict(results)
    assert results[1]["transport_reconnects"] >= 1, dict(results)
    assert results[0]["transport_reconnects"] >= 1, dict(results)
    for r in (0, 1):
        assert results[r]["transport_reconnect_failures"] == 0


def test_python_chaos_counter_reaches_registry():
    from horovod_tpu.utils import metrics as M
    before = M.CHAOS_INJECTIONS.value(kind="stall")
    spec = chaos.parse_spec({"events": [
        {"kind": "stall", "rank": 0, "point": "p", "duration_ms": 0}]})
    ChaosInjector(spec, rank=0, sleep_fn=lambda s: None).maybe_stall("p")
    assert M.CHAOS_INJECTIONS.value(kind="stall") == before + 1
