"""Perf-attribution plane (horovod_tpu/perf/; docs/profiling.md):

  * cost-model golden numbers — param counts for the llama / moe_llama
    bench shapes pinned against the analytical formulas (and the
    formulas pinned against real init() for the tiny configs), the 6N /
    attention FLOPs conventions, the roofline decomposition;
  * the ledger's decomposition-sums-to-step-time invariant, including
    the over-prediction path (components rescaled, drift observable);
  * the native op-stats C API round trip (hvd_core_op_stats), name
    collapse and the cardinality bound's __other__ overflow;
  * the regression gate's pass/fail matrix (median±MAD semantics);
  * the fleet merge verdicts and the doctor --perf rendering.
"""

import json

import numpy as np
import pytest

from horovod_tpu.perf import costmodel as cm
from horovod_tpu.perf import gate
from horovod_tpu.perf.ledger import (PerfLedger, local_verdict,
                                     merge_perf_reports, native_op_stats)


# ------------------------------------------------------------- cost model
def test_llama_param_count_golden():
    # bench.py's default "bench" config (dim 1024, 8 layers, ffn 4096)
    assert cm.llama_param_count(32768, 1024, 8, 16, 8, 4096) == 192955392
    # CONFIGS["tiny"] / ["mini"], pinned against actual init() below
    assert cm.llama_param_count(256, 64, 2, 4, 2, 128) == 106816
    assert cm.llama_param_count(4096, 512, 4, 8, 4, 1024) == 13636096


def test_moe_llama_param_count_golden():
    assert cm.moe_llama_param_count(256, 64, 2, 4, 2, 128, 4) == 189248
    assert cm.moe_llama_param_count(256, 64, 2, 4, 2, 128, 8) == 320832
    # CONFIGS["mini"]: total vs top-1-active
    assert cm.moe_llama_param_count(4096, 256, 4, 8, 4, 512, 8) == 11282688
    assert cm.moe_llama_active_param_count(
        4096, 256, 4, 8, 4, 512, 8, 1) == 3942656
    # active == total when every expert fires
    assert cm.moe_llama_active_param_count(
        4096, 256, 4, 8, 4, 512, 8, 8) == 11282688


def test_llama_param_count_matches_real_init():
    import jax
    from horovod_tpu.models import llama
    cfg = llama.CONFIGS["tiny"]
    actual = sum(int(np.prod(l.shape)) for l in
                 jax.tree_util.tree_leaves(
                     llama.init(jax.random.PRNGKey(0), cfg)))
    assert actual == cm.llama_param_count(
        cfg.vocab, cfg.dim, cfg.n_layers, cfg.n_heads, cfg.n_kv_heads,
        cfg.ffn_dim)


def test_moe_param_count_matches_real_init():
    import jax
    from horovod_tpu.models import moe_llama
    cfg = moe_llama.CONFIGS["tiny"]
    actual = sum(int(np.prod(l.shape)) for l in
                 jax.tree_util.tree_leaves(
                     moe_llama.init(jax.random.PRNGKey(0), cfg)))
    assert actual == cm.moe_llama_param_count(
        cfg.vocab, cfg.dim, cfg.n_layers, cfg.n_heads, cfg.n_kv_heads,
        cfg.moe_hidden, cfg.n_experts)


def test_flops_conventions():
    # the conservative headline convention bench.py's MFU is defined by
    assert cm.train_flops_per_token(1000) == 6000.0
    # attention term: 12·L·s·d, halved causal (the documented convention)
    full = cm.train_flops_per_token(
        0, attention=dict(n_layers=2, dim=64, seq=128, causal=False))
    assert full == 12.0 * 2 * 128 * 64
    causal = cm.train_flops_per_token(
        0, attention=dict(n_layers=2, dim=64, seq=128))
    assert causal == full / 2
    # additive with the 6N term
    assert cm.train_flops_per_token(
        1000, attention=dict(n_layers=2, dim=64, seq=128)) == \
        6000.0 + causal


def test_bench_constants_are_the_cost_model():
    """bench.py must consume THIS table (the unification satellite) —
    a fork of the constants is exactly the drift this plane removes."""
    import bench
    assert bench.PEAK_TFLOPS is cm.PEAK_TFLOPS
    assert cm.peak_flops("v5e") == 197.0e12
    assert cm.peak_flops("unknown-chip") == cm.peak_flops("v5e")


def test_predicted_step_time_roofline():
    pred = cm.predicted_step_time(1e9, 1e6, chip="cpu", link="loopback")
    assert pred["compute_s"] == pytest.approx(1e9 / 0.5e12)
    assert pred["exposed_comm_s"] == pytest.approx(1e6 / 10e9)
    assert pred["step_s"] == pytest.approx(
        pred["compute_s"] + pred["exposed_comm_s"])
    # overlap hides comm; full overlap leaves only compute
    full = cm.predicted_step_time(1e9, 1e6, overlap_fraction=1.0)
    assert full["exposed_comm_s"] == 0.0
    # DCN is the slow fabric: same bytes take longer than ICI
    dcn = cm.predicted_step_time(0, 1e9, link="dcn")
    ici = cm.predicted_step_time(0, 1e9, link="ici")
    assert dcn["exposed_comm_s"] > ici["exposed_comm_s"]
    with pytest.raises(ValueError, match="link"):
        cm.predicted_step_time(1, 1, link="carrier-pigeon")
    with pytest.raises(ValueError, match="overlap_fraction"):
        cm.predicted_step_time(1, 1, overlap_fraction=1.5)


def test_plan_comm_bytes_matches_wire_model():
    """The cost model's comm leg is the plan cache × wire policy × ring
    model — cross-checked against ops/wire.modeled_wire_bytes directly."""
    from horovod_tpu.ops.fusion import make_plan
    from horovod_tpu.ops.wire import modeled_wire_bytes
    shapes = [(1 << 20,), (256,), (64,)]
    dtypes = [np.float32] * 3
    plan = make_plan(shapes, dtypes, 4 << 20)
    out = cm.plan_comm_bytes(plan, "none", {"flat": 8})
    expect = sum(modeled_wire_bytes(sum(b.sizes), 4, "none",
                                    {"flat": 8})["bottleneck"]
                 for b in plan.buckets)
    assert out["bottleneck"] == int(expect)
    # int8 carries 1/4 the bytes of fp32 on every bucket
    out8 = cm.plan_comm_bytes(plan, "int8_ring", {"flat": 8})
    assert out8["bottleneck"] * 4 <= out["bottleneck"] + 4 * len(
        plan.buckets)
    # auto on a two-level mesh routes the big bucket's bytes to DCN
    two = cm.plan_comm_bytes(plan, "auto", {"dcn": 2, "ici": 4})
    assert "dcn" in two["per_fabric"]


# ------------------------------------------------------- ZeRO what-if model
def test_zero_memory_bytes_goldens():
    """The docs/zero.md memory math, exact: N=1000 fp32 params, n=4,
    adam (2 slots)."""
    lv = {l: cm.zero_memory_bytes(l, 1000, 4) for l in (0, 1, 2, 3)}
    assert lv[0] == {"params_bytes": 4000, "grads_bytes": 4000,
                     "opt_state_bytes": 8000, "ef_residual_bytes": 0,
                     "total_bytes": 16000}
    assert lv[1]["total_bytes"] == 4000 + 4000 + 2000
    assert lv[2]["total_bytes"] == 4000 + 1000 + 2000
    assert lv[3]["total_bytes"] == 1000 + 1000 + 2000
    # the acceptance ratios: state+grads >= 2x down at level 2 vs the
    # unsharded baseline on any n >= 2; params n-fold down at level 3
    for n in (2, 4, 8):
        l0 = cm.zero_memory_bytes(0, 1000, n)
        l2 = cm.zero_memory_bytes(2, 1000, n)
        l3 = cm.zero_memory_bytes(3, 1000, n)
        sg0 = l0["grads_bytes"] + l0["opt_state_bytes"]
        sg2 = l2["grads_bytes"] + l2["opt_state_bytes"]
        assert sg0 >= 2 * sg2, (n, sg0, sg2)
        assert l0["params_bytes"] >= (n / 2) * l3["params_bytes"]
    # EF adds a full-size residual per rank (inherent to EF-on-RS)
    assert cm.zero_memory_bytes(2, 1000, 4, ef=True)[
        "ef_residual_bytes"] == 4000
    with pytest.raises(ValueError, match="zero level"):
        cm.zero_memory_bytes(5, 1000, 4)


def test_zero_comm_bytes_wire_claims():
    """RS+AG == AR at k=1 (the arXiv:2004.13336 equal-bytes claim),
    level 2 strictly below level 1 at k>1, level 3 == level 2, and the
    RS leg priced at the wire format's itemsize with exact AG legs."""
    n, N = 8, 1 << 20
    at_k1 = [cm.zero_comm_bytes(N, n, l)["total_bytes"]
             for l in (0, 1, 2, 3)]
    assert len(set(at_k1)) == 1  # all equal
    k = 4
    l1 = cm.zero_comm_bytes(N, n, 1, k=k)
    l2 = cm.zero_comm_bytes(N, n, 2, k=k)
    l3 = cm.zero_comm_bytes(N, n, 3, k=k)
    assert l2["total_bytes"] < l1["total_bytes"]
    assert l3 == l2
    # per-microbatch RS at int8 is 1/4 the fp32 leg; AG stays exact
    q = cm.zero_comm_bytes(N, n, 2, k=k, wire_format="int8_ring")
    assert q["rs_bytes"] * 4 == l2["rs_bytes"]
    assert q["ag_bytes"] == l2["ag_bytes"]
    # single member axis moves nothing
    assert cm.zero_comm_bytes(N, 1, 3)["total_bytes"] == 0.0


def test_zero_level_table_rows():
    rows = cm.zero_level_table(1000, 4, k=2, wire_format="bf16",
                               flops_per_step=1e9, chip="cpu",
                               link="ici")
    assert [r["level"] for r in rows] == [0, 1, 2, 3]
    for r in rows:
        assert r["memory"]["total_bytes"] > 0
        assert r["comm"]["total_bytes"] > 0
        assert r["exposed_comm_s"] == pytest.approx(
            r["comm"]["total_bytes"] / cm.link_bandwidth("ici"))
        assert r["predicted"]["step_s"] > 0
    # memory monotonically non-increasing with level
    mems = [r["memory"]["total_bytes"] for r in rows]
    assert mems == sorted(mems, reverse=True)


def test_ledger_zero_section_and_drift_bound():
    """configure(zero_model=...) makes the report carry the per-level
    what-if table, and on a workload whose step time matches the model
    the ledger's drift ratio sits inside the tested bound — the
    "ledger confirms the prediction" closure (docs/zero.md)."""
    led = PerfLedger()
    comm = cm.zero_comm_bytes(1 << 16, 8, 2, k=2)["total_bytes"]
    led.configure(flops_per_step=1e7, comm_bytes_per_step=comm,
                  chip="cpu", link="loopback",
                  zero_model={"n_params": 1 << 16, "world": 8,
                              "level": 2, "k": 2, "opt_slots": 2})
    assert led.report()["zero"]["levels"]  # table rides steps=0 reports
    pred_t = (1e7 / cm.peak_flops("cpu")
              + comm / cm.link_bandwidth("loopback"))
    for dt in (pred_t * 1.02, pred_t * 0.98, pred_t):
        led.record_step(dt)
    rep = led.report()
    assert rep["zero"]["active_level"] == 2
    levels = rep["zero"]["levels"]
    assert [r["level"] for r in levels] == [0, 1, 2, 3]
    # the active level's table row IS the configured comm model
    assert levels[2]["comm"]["total_bytes"] == int(comm)
    # drift bound: modeled/measured within 5% when the workload matches
    assert 0.95 <= rep["model_drift_ratio"] <= 1.05
    with pytest.raises(ValueError, match="n_params"):
        led.configure(zero_model={"world": 8})


def test_doctor_renders_zero_table():
    from horovod_tpu.runner.doctor import render_perf
    led = PerfLedger()
    led.configure(flops_per_step=1e7, comm_bytes_per_step=1e5,
                  zero_model={"n_params": 1000, "world": 4, "level": 3})
    led.record_step(0.01)
    rep = led.report()
    rep["rank"] = 0
    view = merge_perf_reports({"rank.0": json.dumps(rep).encode()})
    text = render_perf(view)
    assert "ZeRO memory-vs-comm what-if" in text
    assert "active level: 3" in text
    assert text.count("\n  ") >= 4  # the four level rows render


# ----------------------------------------------------------------- ledger
def test_decomposition_sums_to_step_time_exactly():
    led = PerfLedger()
    led.configure(flops_per_step=1e8, comm_bytes_per_step=1e6,
                  chip="cpu", link="loopback")
    led.add_input_wait(0.002)
    for dt in (0.01, 0.02, 0.015):
        row = led.record_step(dt)
        parts = (row["compute_s"] + row["exposed_comm_s"]
                 + row["host_input_s"] + row["stall_s"])
        assert parts == pytest.approx(row["step_time_s"], abs=1e-12)
    rep = led.report()
    assert rep["steps"] == 3
    assert sum(rep["decomposition"].values()) == pytest.approx(
        rep["step_time_s"]["mean"], rel=1e-9)
    assert abs(sum(rep["fractions"].values()) - 1.0) < 1e-9
    assert rep["verdict"] in ("compute-bound", "comm-bound",
                              "input-bound", "stall-bound")
    assert rep["predicted"]["step_s"] > 0


def test_overpredicting_model_rescales_and_records_drift():
    led = PerfLedger()
    # model predicts 2 s of compute; the measured step is 10 ms
    led.configure(flops_per_step=1e12, chip="cpu", link="loopback")
    row = led.record_step(0.01)
    total = (row["compute_s"] + row["exposed_comm_s"]
             + row["host_input_s"] + row["stall_s"])
    assert total == pytest.approx(0.01, abs=1e-12)  # never sums past dt
    assert row["stall_s"] == 0.0
    rep = led.report()
    assert rep["model_drift_ratio"] > 10  # the overshoot is observable
    assert rep["predicted_vs_measured"]["step_ratio"] > 10


def test_input_wait_is_capped_and_consumed():
    led = PerfLedger()
    led.add_input_wait(5.0)              # absurd wait vs a 10 ms step
    row = led.record_step(0.01)
    assert row["host_input_s"] == pytest.approx(0.01)
    row2 = led.record_step(0.01)         # consumed: next step starts clean
    assert row2["host_input_s"] == 0.0


def test_timed_step_and_global_api():
    import horovod_tpu.perf as perf
    perf.reset()
    with perf.timed_step():
        pass
    rep = perf.report()
    assert rep["steps"] == 1
    assert rep["step_time_s"]["mean"] >= 0.0
    perf.reset()
    assert perf.report()["steps"] == 0


def test_configure_validation():
    led = PerfLedger()
    with pytest.raises(ValueError, match="link"):
        led.configure(link="warp-drive")
    with pytest.raises(ValueError, match="overlap_fraction"):
        led.configure(overlap_fraction=2.0)


def test_perf_knob_validation():
    from horovod_tpu.common.knobs import Knobs
    from horovod_tpu.perf import resolve_link, validate_perf_knobs
    validate_perf_knobs(Knobs())  # defaults pass
    with pytest.raises(ValueError, match="HOROVOD_PERF_LINK"):
        validate_perf_knobs(Knobs({"HOROVOD_PERF_LINK": "wormhole"}))
    with pytest.raises(ValueError, match="HOROVOD_PERF_INTERVAL"):
        validate_perf_knobs(Knobs({"HOROVOD_PERF_INTERVAL": -1.0}))
    assert resolve_link(Knobs({"HOROVOD_PERF_LINK": "dcn"})) == "dcn"
    assert resolve_link(Knobs()) == "loopback"  # auto, no mesh


def test_loader_prefetch_accounts_input_wait():
    """data/loader.prefetch feeds the ledger's host_input component."""
    import time

    import horovod_tpu.perf as perf
    from horovod_tpu.data.loader import prefetch
    perf.reset()

    def slow_batches():
        for i in range(3):
            time.sleep(0.005)
            yield i

    out = list(prefetch(slow_batches(), depth=1, transfer=lambda b: b))
    assert out == [0, 1, 2]
    row = perf.record_step(1.0)
    assert row["host_input_s"] > 0.0
    perf.reset()


# -------------------------------------------------------------- native leg
def test_op_stats_c_api_round_trip():
    import time

    from horovod_tpu.common.basics import (OP_ALLREDUCE, CoordinationCore,
                                           LoopbackHub)
    hub = LoopbackHub(2)
    cores = [CoordinationCore.loopback(hub, r, cycle_ms=0.5)
             for r in range(2)]
    try:
        for i in range(3):
            for c in cores:
                # per-call unique suffixes must COLLAPSE to one key
                c.submit(f"grad.noname.{i}", "f32:8:sum", OP_ALLREDUCE,
                         64)
            for c in cores:
                r = c.wait(10.0)
                assert r is not None and r.type == "OK", r
        for c in cores:
            stats = c.op_stats()
            assert set(stats) == {"grad"}, stats
            s = stats["grad"]
            assert s["count"] == 3
            assert s["bytes"] == 3 * 64
            assert s["sum_us"] >= s["max_us"] > 0
        # the report's native leg reads the same aggregates
        rows = native_op_stats(cores[0])
        assert rows and rows[0]["name"] == "grad"
        assert rows[0]["mean_us"] == pytest.approx(
            cores[0].op_stats()["grad"]["sum_us"] / 3)
    finally:
        for c in cores:
            c.shutdown()
        time.sleep(0.3)
        for c in cores:
            c.close()
        hub.close()


def test_op_stats_distinct_names_and_join_excluded():
    import time

    from horovod_tpu.common.basics import (OP_ALLREDUCE, OP_BROADCAST,
                                           CoordinationCore, LoopbackHub)
    hub = LoopbackHub(1)
    core = CoordinationCore.loopback(hub, 0, cycle_ms=0.5)
    try:
        core.submit("a", "f32:4:sum", OP_ALLREDUCE, 16)
        assert core.wait(10.0).type == "OK"
        core.submit("b", "f32:4:bcast", OP_BROADCAST, 8)
        assert core.wait(10.0).type == "OK"
        stats = core.op_stats()
        assert set(stats) == {"a", "b"}, stats
        assert stats["a"]["bytes"] == 16
        assert stats["b"]["bytes"] == 8
    finally:
        core.shutdown()
        time.sleep(0.2)
        core.close()
        hub.close()


# ------------------------------------------------------------------- gate
def _art(value, metric="llama train tokens/sec/chip (cpu, run detail)",
         unit="tokens/sec/chip"):
    return {"metric": metric, "value": value, "unit": unit}


def test_gate_metric_key_strips_run_detail():
    a = _art(1.0, "llama train tokens/sec/chip (cpu, loss 5.9->5.0)")
    b = _art(2.0, "llama train tokens/sec/chip (v5e, loss 4.2->4.0)")
    assert gate.metric_key(a) == gate.metric_key(b)


def test_gate_pass_fail_matrix():
    doc = gate.empty_baseline()
    gate.update_baseline(doc, [_art(v) for v in (100.0, 102.0, 98.0)])
    # unmodified re-run: within noise -> pass
    res = gate.check_artifacts(doc, [_art(101.0)])
    assert not res["failed"]
    key = next(iter(res["results"]))
    assert res["results"][key]["status"] == "pass"
    # 2x slowdown (tokens/sec halves) -> regression
    res = gate.check_artifacts(doc, [_art(50.0)])
    assert res["failed"]
    assert next(iter(res["results"].values()))["status"] == "regression"
    # 2x speedup -> improved, NOT a failure
    res = gate.check_artifacts(doc, [_art(200.0)])
    assert not res["failed"]
    assert next(iter(res["results"].values()))["status"] == "improved"
    # unknown key -> no-baseline, not a failure
    res = gate.check_artifacts(doc, [_art(5.0, metric="new mode",
                                          unit="GB/s")])
    assert not res["failed"]
    assert next(iter(res["results"].values()))["status"] == "no-baseline"


def test_gate_lower_is_better_units():
    doc = gate.empty_baseline()
    art = {"metric": "step time", "value": 0.1, "unit": "seconds"}
    gate.update_baseline(doc, [art])
    worse = dict(art, value=0.25)
    assert gate.check_artifacts(doc, [worse])["failed"]
    better = dict(art, value=0.05)
    assert not gate.check_artifacts(doc, [better])["failed"]


def test_gate_zero_mad_uses_relative_floor():
    doc = gate.empty_baseline()
    gate.update_baseline(doc, [_art(100.0)])  # singleton: MAD 0
    # 5% off: under the 10% floor -> pass despite zero MAD
    assert not gate.check_artifacts(doc, [_art(95.0)])["failed"]
    assert gate.check_artifacts(doc, [_art(80.0)])["failed"]


def test_gate_noisy_baseline_tolerates_jitter():
    doc = gate.empty_baseline()
    gate.update_baseline(doc, [_art(v) for v in
                               (80.0, 120.0, 100.0, 90.0, 110.0)])
    # well inside the MAD band of a noisy baseline
    assert not gate.check_artifacts(doc, [_art(75.0)])["failed"]


def test_gate_rolling_window_and_file_round_trip(tmp_path):
    doc = gate.empty_baseline()
    for i in range(gate.MAX_BASELINE_VALUES + 7):
        gate.update_baseline(doc, [_art(float(i))])
    entry = next(iter(doc["entries"].values()))
    assert len(entry["values"]) == gate.MAX_BASELINE_VALUES
    path = str(tmp_path / "baseline.json")
    gate.save_baseline(path, doc)
    again = gate.load_baseline(path)
    assert again == doc
    with pytest.raises(ValueError, match="schema"):
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            json.dump({"schema": "nope"}, f)
        gate.load_baseline(bad)


def test_gate_ignores_invalid_bench_rows():
    doc = gate.empty_baseline()
    invalid = {"metric": "BENCH_INVALID", "value": 0, "unit": "error"}
    assert gate.update_baseline(doc, [invalid]) == []
    assert not gate.check_artifacts(doc, [invalid])["failed"]


def test_committed_baseline_ledger_loads():
    """The committed trajectory ledger must stay parseable — it is the
    gate's reference point (docs/profiling.md#regression-gate)."""
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PERF_BASELINE.json")
    doc = gate.load_baseline(path)
    assert doc["entries"], "committed baseline has no entries"
    for key, entry in doc["entries"].items():
        assert entry["values"], key


# ------------------------------------------------------------ fleet merge
def _rank_report(rank, step_s, comp=None):
    led = PerfLedger()
    if comp:
        led.configure(**comp)
    for _ in range(3):
        led.record_step(step_s)
    rep = led.report()
    rep["rank"] = rank
    return rep


def test_merge_straggler_verdict_outranks_components():
    stored = {
        "rank.0": json.dumps(_rank_report(0, 0.01)).encode(),
        "rank.1": json.dumps(_rank_report(1, 0.01)).encode(),
        "rank.2": json.dumps(_rank_report(2, 0.05)).encode(),
    }
    view = merge_perf_reports(stored)
    assert view["fleet"]["verdict"] == "straggler-bound"
    assert view["fleet"]["straggler"]["rank"] == "2"
    assert set(view["ranks"]) == {"0", "1", "2"}


def test_merge_component_verdict_and_torn_put():
    comp = dict(flops_per_step=1e6, comm_bytes_per_step=8e7,
                chip="cpu", link="loopback")  # comm 8 ms >> compute 2 µs
    stored = {
        "rank.0": json.dumps(_rank_report(0, 0.01, comp)).encode(),
        "rank.1": json.dumps(_rank_report(1, 0.011, comp)).encode(),
        "rank.2": b"{torn json",  # must not 500 the view
    }
    view = merge_perf_reports(stored)
    assert view["fleet"]["verdict"] == "comm-bound"
    assert set(view["ranks"]) == {"0", "1"}


def test_local_verdict_dominant_component():
    assert local_verdict({"compute_s": 0.9, "exposed_comm_s": 0.05,
                          "host_input_s": 0.0, "stall_s": 0.05}) == \
        "compute-bound"
    assert local_verdict({"compute_s": 0.1, "exposed_comm_s": 0.1,
                          "host_input_s": 0.7, "stall_s": 0.1}) == \
        "input-bound"


# ----------------------------------------------------------------- doctor
def test_doctor_perf_render_and_file_source(tmp_path):
    from horovod_tpu.runner.doctor import load_perf_view, render_perf
    stored = {
        "rank.0": json.dumps(_rank_report(0, 0.01)).encode(),
        "rank.1": json.dumps(_rank_report(1, 0.05)).encode(),
    }
    view = merge_perf_reports(stored)
    text = render_perf(view)
    assert "BOTTLENECK: straggler-bound" in text
    assert "rank 1" in text and "rank 0: step 10.00ms" in text
    # file + directory sources resolve to the same rendering
    path = tmp_path / "perf.json"
    path.write_text(json.dumps(view))
    assert render_perf(load_perf_view(str(path))) == text
    assert render_perf(load_perf_view(str(tmp_path))) == text
    # a saved single-rank hvd.perf_report() payload wraps cleanly
    single = tmp_path / "single.json"
    single.write_text(json.dumps(_rank_report(0, 0.02)))
    text1 = render_perf(load_perf_view(str(single)))
    assert "1 rank(s)" in text1


def test_doctor_perf_cli_dispatch(tmp_path, capsys):
    from horovod_tpu.runner.doctor import main as doctor_main
    stored = {"rank.0": json.dumps(_rank_report(0, 0.01)).encode()}
    path = tmp_path / "perf.json"
    path.write_text(json.dumps(merge_perf_reports(stored)))
    assert doctor_main(["--perf", str(path)]) == 0
    out = capsys.readouterr().out
    assert "step-time attribution" in out
    assert doctor_main(["--perf", str(tmp_path / "missing.json")]) == 2


def test_empty_perf_view_renders_hint():
    from horovod_tpu.runner.doctor import render_perf
    text = render_perf(merge_perf_reports({}))
    assert "no perf reports recorded" in text
