"""Native coordination core tests (ctypes -> csrc/libhvd_tpu_core.so).

Reference analogs: controller negotiation/consistency tests embedded in
test/parallel/* error-path assertions; multi-rank protocol exercised with
in-process loopback ranks (threads) and real TCP over localhost processes
(the reference uses real gloo/MPI over loopback the same way, SURVEY.md §4).
"""

import json
import multiprocessing
import os
import threading
import time

import pytest

from horovod_tpu.common.basics import (CoordinationCore, LoopbackHub,
                                       OP_ALLREDUCE, OP_ALLGATHER)
from horovod_tpu.common.exceptions import DuplicateTensorNameError


@pytest.fixture
def hub2():
    hub = LoopbackHub(2)
    cores = [CoordinationCore.loopback(hub, r, cycle_ms=0.2)
             for r in range(2)]
    yield cores
    for c in cores:
        c.shutdown()
    for c in cores:
        c.close()
    hub.close()


def test_loopback_negotiation_basic(hub2):
    c0, c1 = hub2
    assert c0.rank() == 0 and c0.size() == 2
    c0.submit("grad/w", "f32:4x4:sum", OP_ALLREDUCE, 64)
    # not globally ready until rank 1 submits
    assert c0.poll() is None
    time.sleep(0.05)
    assert c0.poll() is None
    c1.submit("grad/w", "f32:4x4:sum", OP_ALLREDUCE, 64)
    r0 = c0.wait(5.0)
    r1 = c1.wait(5.0)
    assert r0 is not None and r1 is not None
    assert r0.type == "OK" and r0.names == ["grad/w"]
    assert r1.names == ["grad/w"]


def test_ordering_agreement_under_reversed_submission(hub2):
    """The controller's whole point: ranks submit in different orders but
    receive one agreed order (deadlock avoidance, controller.cc:69-450)."""
    c0, c1 = hub2
    c0.submit("a", "f32:8:sum", OP_ALLREDUCE, 32)
    c0.submit("b", "f32:8:sum", OP_ALLREDUCE, 32)
    time.sleep(0.02)  # ensure rank 0's order is a,b before rank 1 submits
    c1.submit("b", "f32:8:sum", OP_ALLREDUCE, 32)
    c1.submit("a", "f32:8:sum", OP_ALLREDUCE, 32)
    seq0, seq1 = [], []
    deadline = time.time() + 5
    while len(seq0) < 2 and time.time() < deadline:
        r = c0.poll()
        if r:
            seq0.extend(r.names)
        r = c1.poll()
        if r:
            seq1.extend(r.names)
        time.sleep(0.005)
    while len(seq1) < 2 and time.time() < deadline:
        r = c1.poll()
        if r:
            seq1.extend(r.names)
        time.sleep(0.005)
    assert sorted(seq0) == ["a", "b"]
    assert seq0 == seq1, "ranks disagreed on execution order"


def test_signature_mismatch_yields_error(hub2):
    """Shape/dtype mismatch across ranks becomes an ERROR response, not a
    hang (reference: controller.cc:482-707)."""
    c0, c1 = hub2
    c0.submit("t", "f32:4x4:sum", OP_ALLREDUCE, 64)
    c1.submit("t", "f32:2x2:sum", OP_ALLREDUCE, 16)
    r = c0.wait(5.0)
    assert r is not None and r.type == "ERROR"
    assert "inconsistent" in r.error
    assert "t" in r.names


def test_fusion_groups_small_tensors():
    """Small same-dtype tensors fuse into one response batch under the
    threshold (reference: FuseResponses controller.cc:778-915).

    Uses its own hub with a LONG cycle (50 ms) so all eight submits land
    inside one negotiation window even on a loaded machine — with the
    suite-default 0.2 ms cycle, a scheduler hiccup can split the
    submissions across cycles and the batch arrives in two pieces."""
    hub = LoopbackHub(2)
    cores = [CoordinationCore.loopback(hub, r, cycle_ms=50.0)
             for r in range(2)]
    try:
        c0, _ = cores
        for c in cores:
            for i in range(4):
                c.submit(f"g{i}", "f32:10:sum", OP_ALLREDUCE, 40)
        r = c0.wait(5.0)
        assert r is not None and r.type == "OK"
        assert len(r.names) == 4, r.names  # all fused
        assert r.total_bytes == 160
    finally:
        for c in cores:
            c.shutdown()
        for c in cores:
            c.close()
        hub.close()


def test_fusion_respects_dtype_boundary(hub2):
    c0, c1 = hub2
    for c in (c0, c1):
        c.submit("x", "f32:10:sum", OP_ALLREDUCE, 40)
        c.submit("y", "f16:10:sum", OP_ALLREDUCE, 20)
    names_batches = []
    deadline = time.time() + 5
    while len(names_batches) < 2 and time.time() < deadline:
        r = c0.poll()
        if r:
            names_batches.append(r.names)
        time.sleep(0.005)
    assert ["x"] in names_batches and ["y"] in names_batches


def test_duplicate_name_rejected(hub2):
    c0, _ = hub2
    c0.submit("dup", "f32:1:sum", OP_ALLREDUCE, 4)
    with pytest.raises(DuplicateTensorNameError):
        c0.submit("dup", "f32:1:sum", OP_ALLREDUCE, 4)


def test_reserved_delimiters_rejected(hub2):
    c0, _ = hub2
    with pytest.raises(ValueError):
        c0.submit("bad|name", "f32:1:sum", OP_ALLREDUCE, 4)


def test_join_protocol(hub2):
    """Joined rank auto-contributes; all-join emits JOIN_DONE (reference:
    controller.cc:254-307, JoinOp collective_operations.cc:262-270)."""
    c0, c1 = hub2
    c1.join()                # rank 1 out of data
    c0.submit("g", "f32:4:sum", OP_ALLREDUCE, 16)
    r = c0.wait(5.0)
    assert r is not None and r.type == "OK" and r.names == ["g"]
    c0.join()                # now both joined
    r = c0.wait(5.0)
    assert r is not None and r.type == "JOIN_DONE"


def test_cache_hits_on_repeat_steps(hub2):
    c0, c1 = hub2
    for step in range(3):
        for c in (c0, c1):
            c.submit("gw", "f32:100:sum", OP_ALLREDUCE, 400)
        assert c0.wait(5.0) is not None
        assert c1.wait(5.0) is not None
    stats = c0.stats()
    assert stats["cache_hits"] >= 2, stats
    assert stats["cycles"] > 0


def test_cache_fast_path_collapses_cycle_bytes(hub2):
    """Steady-state cycles ship fixed-size bit-vectors, not request lists:
    after the first negotiation of a repeated workload, coordination bytes
    per step collapse (reference: response_cache.h:44-100, bit-vector sync
    at controller.cc:751-776)."""
    c0, c1 = hub2
    names = [f"layer_{i:03d}/kernel/gradient" for i in range(50)]

    def one_step():
        for c in (c0, c1):
            for n in names:
                c.submit(n, "f32:128x128:sum", OP_ALLREDUCE, 65536)
        for c in (c0, c1):
            got = []
            deadline = time.time() + 5
            while len(got) < len(names) and time.time() < deadline:
                r = c.poll()
                if r:
                    assert r.type == "OK"
                    got.extend(r.names)
                time.sleep(0.002)
            assert sorted(got) == sorted(names)

    one_step()
    s1 = c0.stats()
    step1_bytes = s1["bytes_gathered"] + s1["bytes_broadcast"]
    assert step1_bytes > 2000, step1_bytes  # full request lists went out
    one_step()
    one_step()
    s3 = c0.stats()
    delta_bytes = (s3["bytes_gathered"] + s3["bytes_broadcast"]
                   - step1_bytes)
    delta_cycles = s3["cycles"] - s1["cycles"]
    # Every post-negotiation cycle — the two cached steps AND the idle
    # cycles between them — costs bit-vector bytes (~60 for 50 slots), never
    # request-list bytes (~7KB for 50 tensors).
    avg = delta_bytes / max(delta_cycles, 1)
    assert avg < 150, (step1_bytes, delta_bytes, delta_cycles, avg)
    assert s3["cached_responses"] > 0
    assert s3["cache_hits"] >= 2 * len(names)


def test_cache_invalidation_on_signature_change(hub2):
    """A resubmission with a new signature invalidates the cached entry and
    renegotiates cleanly (reference: ResponseCache INVALID state)."""
    c0, c1 = hub2
    for c in (c0, c1):
        c.submit("t", "f32:4:sum", OP_ALLREDUCE, 16)
    assert c0.wait(5.0).sigs == ["f32:4:sum"]
    assert c1.wait(5.0) is not None
    # repeat -> cached
    for c in (c0, c1):
        c.submit("t", "f32:4:sum", OP_ALLREDUCE, 16)
    assert c0.wait(5.0).sigs == ["f32:4:sum"]
    assert c1.wait(5.0) is not None
    # shape change -> invalidate + renegotiate, new signature wins
    for c in (c0, c1):
        c.submit("t", "f32:8:sum", OP_ALLREDUCE, 32)
    r = c0.wait(5.0)
    assert r is not None and r.type == "OK" and r.sigs == ["f32:8:sum"]
    assert c1.wait(5.0) is not None
    # and the new signature is cacheable again
    for c in (c0, c1):
        c.submit("t", "f32:8:sum", OP_ALLREDUCE, 32)
    assert c0.wait(5.0).sigs == ["f32:8:sum"]
    assert c1.wait(5.0) is not None
    assert c0.stats()["cached_responses"] >= 2


def test_cache_agreement_with_joined_rank(hub2):
    """A joined rank counts as agreeing with every cached tensor
    (reference: joined ranks set all cache bits, controller.cc:254-307)."""
    c0, c1 = hub2
    for c in (c0, c1):
        c.submit("g", "f32:4:sum", OP_ALLREDUCE, 16)
    assert c0.wait(5.0) is not None
    assert c1.wait(5.0) is not None
    c1.join()
    c0.submit("g", "f32:4:sum", OP_ALLREDUCE, 16)  # cache hit on rank 0
    r = c0.wait(5.0)
    assert r is not None and r.type == "OK" and r.names == ["g"]


def test_eviction_recovers_inflight_hit_request():
    """Capacity eviction of a slot whose hit bit is still awaiting agreement
    must re-materialize the request, not drop it (regression: the submitter
    cannot resubmit past the DUPLICATE_NAME guard, so a dropped request
    hangs the collective forever)."""
    hub = LoopbackHub(2)
    cap = 4
    c0, c1 = [CoordinationCore.loopback(hub, r, cycle_ms=0.2,
                                        cache_capacity=cap)
               for r in range(2)]
    try:
        def drain(c, want):
            got = []
            deadline = time.time() + 5
            while len(got) < want and time.time() < deadline:
                r = c.poll()
                if r:
                    assert r.type == "OK", r
                    got.extend(r.names)
                time.sleep(0.002)
            return got

        # Fill the replica: a,b,c,d negotiated and cached on both ranks.
        for nm in "abcd":
            for c in (c0, c1):
                c.submit(nm, "f32:4:sum", OP_ALLREDUCE, 16)
        assert sorted(drain(c0, 4)) == list("abcd")
        assert sorted(drain(c1, 4)) == list("abcd")

        # Rank 0 hits cached 'a'; rank 1 stays silent so no agreement.
        c0.submit("a", "f32:4:sum", OP_ALLREDUCE, 16)
        time.sleep(0.05)
        # Both ranks negotiate new tensors that force FIFO eviction of 'a'.
        for nm in ("e0", "e1", "e2"):
            for c in (c0, c1):
                c.submit(nm, "f32:4:sum", OP_ALLREDUCE, 16)
        assert sorted(drain(c0, 3)) == ["e0", "e1", "e2"]
        assert sorted(drain(c1, 3)) == ["e0", "e1", "e2"]
        # Now rank 1 submits 'a': rank 0's evicted-but-rematerialized
        # request must meet it on the full path.
        c1.submit("a", "f32:4:sum", OP_ALLREDUCE, 16)
        assert drain(c0, 1) == ["a"]
        assert drain(c1, 1) == ["a"]
    finally:
        for c in (c0, c1):
            c.shutdown()
        for c in (c0, c1):
            c.close()
        hub.close()


def _tcp_worker(rank, size, port, results):
    core = CoordinationCore.tcp(rank, size, "127.0.0.1", port,
                                cycle_ms=0.2)
    core.submit(f"t", "f32:8:sum", OP_ALLREDUCE, 32)
    r = core.wait(10.0)
    results[rank] = (r.type, tuple(r.names)) if r else None
    core.shutdown()
    # drain until shutdown completes so ranks exit cleanly
    time.sleep(0.2)
    core.close()


def test_tcp_transport_two_processes():
    """Real multi-process negotiation over localhost TCP (the reference's
    'real gloo over loopback' test strategy, SURVEY.md §4)."""
    port = 29517
    # spawn, not fork: the test session has live jax/XLA threads and a
    # forked child can deadlock on inherited lock state.
    ctx = multiprocessing.get_context("spawn")
    mgr = ctx.Manager()
    results = mgr.dict()
    procs = [ctx.Process(target=_tcp_worker, args=(r, 2, port, results))
             for r in range(2)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=30)
        assert not p.is_alive(), "tcp worker hung"
        assert p.exitcode == 0
    assert results[0] == ("OK", ("t",))
    assert results[1] == ("OK", ("t",))
