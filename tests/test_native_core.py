"""Native coordination core tests (ctypes -> csrc/libhvd_tpu_core.so).

Reference analogs: controller negotiation/consistency tests embedded in
test/parallel/* error-path assertions; multi-rank protocol exercised with
in-process loopback ranks (threads) and real TCP over localhost processes
(the reference uses real gloo/MPI over loopback the same way, SURVEY.md §4).
"""

import json
import multiprocessing
import os
import threading
import time

import pytest

from horovod_tpu.common.basics import (CoordinationCore, LoopbackHub,
                                       OP_ALLREDUCE, OP_ALLGATHER)
from horovod_tpu.common.exceptions import DuplicateTensorNameError


@pytest.fixture
def hub2():
    hub = LoopbackHub(2)
    cores = [CoordinationCore.loopback(hub, r, cycle_ms=0.2)
             for r in range(2)]
    yield cores
    for c in cores:
        c.shutdown()
    for c in cores:
        c.close()
    hub.close()


def test_loopback_negotiation_basic(hub2):
    c0, c1 = hub2
    assert c0.rank() == 0 and c0.size() == 2
    c0.submit("grad/w", "f32:4x4:sum", OP_ALLREDUCE, 64)
    # not globally ready until rank 1 submits
    assert c0.poll() is None
    time.sleep(0.05)
    assert c0.poll() is None
    c1.submit("grad/w", "f32:4x4:sum", OP_ALLREDUCE, 64)
    r0 = c0.wait(5.0)
    r1 = c1.wait(5.0)
    assert r0 is not None and r1 is not None
    assert r0.type == "OK" and r0.names == ["grad/w"]
    assert r1.names == ["grad/w"]


def test_ordering_agreement_under_reversed_submission(hub2):
    """The controller's whole point: ranks submit in different orders but
    receive one agreed order (deadlock avoidance, controller.cc:69-450)."""
    c0, c1 = hub2
    c0.submit("a", "f32:8:sum", OP_ALLREDUCE, 32)
    c0.submit("b", "f32:8:sum", OP_ALLREDUCE, 32)
    time.sleep(0.02)  # ensure rank 0's order is a,b before rank 1 submits
    c1.submit("b", "f32:8:sum", OP_ALLREDUCE, 32)
    c1.submit("a", "f32:8:sum", OP_ALLREDUCE, 32)
    seq0, seq1 = [], []
    deadline = time.time() + 5
    while len(seq0) < 2 and time.time() < deadline:
        r = c0.poll()
        if r:
            seq0.extend(r.names)
        r = c1.poll()
        if r:
            seq1.extend(r.names)
        time.sleep(0.005)
    while len(seq1) < 2 and time.time() < deadline:
        r = c1.poll()
        if r:
            seq1.extend(r.names)
        time.sleep(0.005)
    assert sorted(seq0) == ["a", "b"]
    assert seq0 == seq1, "ranks disagreed on execution order"


def test_signature_mismatch_yields_error(hub2):
    """Shape/dtype mismatch across ranks becomes an ERROR response, not a
    hang (reference: controller.cc:482-707)."""
    c0, c1 = hub2
    c0.submit("t", "f32:4x4:sum", OP_ALLREDUCE, 64)
    c1.submit("t", "f32:2x2:sum", OP_ALLREDUCE, 16)
    r = c0.wait(5.0)
    assert r is not None and r.type == "ERROR"
    assert "inconsistent" in r.error
    assert "t" in r.names


def test_fusion_groups_small_tensors():
    """Small same-dtype tensors fuse into one response batch under the
    threshold (reference: FuseResponses controller.cc:778-915).

    Uses its own hub with a LONG cycle (50 ms) so all eight submits land
    inside one negotiation window even on a loaded machine — with the
    suite-default 0.2 ms cycle, a scheduler hiccup can split the
    submissions across cycles and the batch arrives in two pieces."""
    hub = LoopbackHub(2)
    cores = [CoordinationCore.loopback(hub, r, cycle_ms=50.0)
             for r in range(2)]
    try:
        c0, _ = cores
        for c in cores:
            for i in range(4):
                c.submit(f"g{i}", "f32:10:sum", OP_ALLREDUCE, 40)
        r = c0.wait(5.0)
        assert r is not None and r.type == "OK"
        assert len(r.names) == 4, r.names  # all fused
        assert r.total_bytes == 160
    finally:
        for c in cores:
            c.shutdown()
        for c in cores:
            c.close()
        hub.close()


def test_fusion_respects_dtype_boundary(hub2):
    c0, c1 = hub2
    for c in (c0, c1):
        c.submit("x", "f32:10:sum", OP_ALLREDUCE, 40)
        c.submit("y", "f16:10:sum", OP_ALLREDUCE, 20)
    names_batches = []
    deadline = time.time() + 5
    while len(names_batches) < 2 and time.time() < deadline:
        r = c0.poll()
        if r:
            names_batches.append(r.names)
        time.sleep(0.005)
    assert ["x"] in names_batches and ["y"] in names_batches


def test_duplicate_name_rejected(hub2):
    c0, _ = hub2
    c0.submit("dup", "f32:1:sum", OP_ALLREDUCE, 4)
    with pytest.raises(DuplicateTensorNameError):
        c0.submit("dup", "f32:1:sum", OP_ALLREDUCE, 4)


def test_reserved_delimiters_rejected(hub2):
    c0, _ = hub2
    with pytest.raises(ValueError):
        c0.submit("bad|name", "f32:1:sum", OP_ALLREDUCE, 4)


def test_join_protocol(hub2):
    """Joined rank auto-contributes; all-join emits JOIN_DONE (reference:
    controller.cc:254-307, JoinOp collective_operations.cc:262-270)."""
    c0, c1 = hub2
    c1.join()                # rank 1 out of data
    c0.submit("g", "f32:4:sum", OP_ALLREDUCE, 16)
    r = c0.wait(5.0)
    assert r is not None and r.type == "OK" and r.names == ["g"]
    c0.join()                # now both joined
    r = c0.wait(5.0)
    assert r is not None and r.type == "JOIN_DONE"


def test_cache_hits_on_repeat_steps(hub2):
    c0, c1 = hub2
    for step in range(3):
        for c in (c0, c1):
            c.submit("gw", "f32:100:sum", OP_ALLREDUCE, 400)
        assert c0.wait(5.0) is not None
        assert c1.wait(5.0) is not None
    stats = c0.stats()
    assert stats["cache_hits"] >= 2, stats
    assert stats["cycles"] > 0


def test_cache_fast_path_collapses_cycle_bytes(hub2):
    """Steady-state cycles ship fixed-size bit-vectors, not request lists:
    after the first negotiation of a repeated workload, coordination bytes
    per step collapse (reference: response_cache.h:44-100, bit-vector sync
    at controller.cc:751-776)."""
    c0, c1 = hub2
    names = [f"layer_{i:03d}/kernel/gradient" for i in range(50)]

    def one_step():
        for c in (c0, c1):
            for n in names:
                c.submit(n, "f32:128x128:sum", OP_ALLREDUCE, 65536)
        for c in (c0, c1):
            got = []
            deadline = time.time() + 5
            while len(got) < len(names) and time.time() < deadline:
                r = c.poll()
                if r:
                    assert r.type == "OK"
                    got.extend(r.names)
                time.sleep(0.002)
            assert sorted(got) == sorted(names)

    one_step()
    s1 = c0.stats()
    step1_bytes = s1["bytes_gathered"] + s1["bytes_broadcast"]
    assert step1_bytes > 2000, step1_bytes  # full request lists went out
    one_step()
    one_step()
    s3 = c0.stats()
    delta_bytes = (s3["bytes_gathered"] + s3["bytes_broadcast"]
                   - step1_bytes)
    delta_cycles = s3["cycles"] - s1["cycles"]
    # Every post-negotiation cycle — the two cached steps AND the idle
    # cycles between them — costs bit-vector bytes (~60 for 50 slots), never
    # request-list bytes (~7KB for 50 tensors).
    avg = delta_bytes / max(delta_cycles, 1)
    assert avg < 150, (step1_bytes, delta_bytes, delta_cycles, avg)
    assert s3["cached_responses"] > 0
    assert s3["cache_hits"] >= 2 * len(names)


def test_cache_invalidation_on_signature_change(hub2):
    """A resubmission with a new signature invalidates the cached entry and
    renegotiates cleanly (reference: ResponseCache INVALID state)."""
    c0, c1 = hub2
    for c in (c0, c1):
        c.submit("t", "f32:4:sum", OP_ALLREDUCE, 16)
    assert c0.wait(5.0).sigs == ["f32:4:sum"]
    assert c1.wait(5.0) is not None
    # repeat -> cached
    for c in (c0, c1):
        c.submit("t", "f32:4:sum", OP_ALLREDUCE, 16)
    assert c0.wait(5.0).sigs == ["f32:4:sum"]
    assert c1.wait(5.0) is not None
    # shape change -> invalidate + renegotiate, new signature wins
    for c in (c0, c1):
        c.submit("t", "f32:8:sum", OP_ALLREDUCE, 32)
    r = c0.wait(5.0)
    assert r is not None and r.type == "OK" and r.sigs == ["f32:8:sum"]
    assert c1.wait(5.0) is not None
    # and the new signature is cacheable again
    for c in (c0, c1):
        c.submit("t", "f32:8:sum", OP_ALLREDUCE, 32)
    assert c0.wait(5.0).sigs == ["f32:8:sum"]
    assert c1.wait(5.0) is not None
    assert c0.stats()["cached_responses"] >= 2


def test_cache_agreement_with_joined_rank(hub2):
    """A joined rank counts as agreeing with every cached tensor
    (reference: joined ranks set all cache bits, controller.cc:254-307)."""
    c0, c1 = hub2
    for c in (c0, c1):
        c.submit("g", "f32:4:sum", OP_ALLREDUCE, 16)
    assert c0.wait(5.0) is not None
    assert c1.wait(5.0) is not None
    c1.join()
    c0.submit("g", "f32:4:sum", OP_ALLREDUCE, 16)  # cache hit on rank 0
    r = c0.wait(5.0)
    assert r is not None and r.type == "OK" and r.names == ["g"]


def test_eviction_recovers_inflight_hit_request():
    """Capacity eviction of a slot whose hit bit is still awaiting agreement
    must re-materialize the request, not drop it (regression: the submitter
    cannot resubmit past the DUPLICATE_NAME guard, so a dropped request
    hangs the collective forever)."""
    hub = LoopbackHub(2)
    cap = 4
    c0, c1 = [CoordinationCore.loopback(hub, r, cycle_ms=0.2,
                                        cache_capacity=cap)
               for r in range(2)]
    try:
        def drain(c, want):
            got = []
            deadline = time.time() + 5
            while len(got) < want and time.time() < deadline:
                r = c.poll()
                if r:
                    assert r.type == "OK", r
                    got.extend(r.names)
                time.sleep(0.002)
            return got

        # Fill the replica: a,b,c,d negotiated and cached on both ranks.
        for nm in "abcd":
            for c in (c0, c1):
                c.submit(nm, "f32:4:sum", OP_ALLREDUCE, 16)
        assert sorted(drain(c0, 4)) == list("abcd")
        assert sorted(drain(c1, 4)) == list("abcd")

        # Rank 0 hits cached 'a'; rank 1 stays silent so no agreement.
        c0.submit("a", "f32:4:sum", OP_ALLREDUCE, 16)
        time.sleep(0.05)
        # Both ranks negotiate new tensors that force FIFO eviction of 'a'.
        for nm in ("e0", "e1", "e2"):
            for c in (c0, c1):
                c.submit(nm, "f32:4:sum", OP_ALLREDUCE, 16)
        assert sorted(drain(c0, 3)) == ["e0", "e1", "e2"]
        assert sorted(drain(c1, 3)) == ["e0", "e1", "e2"]
        # Now rank 1 submits 'a': rank 0's evicted-but-rematerialized
        # request must meet it on the full path.
        c1.submit("a", "f32:4:sum", OP_ALLREDUCE, 16)
        assert drain(c0, 1) == ["a"]
        assert drain(c1, 1) == ["a"]
    finally:
        for c in (c0, c1):
            c.shutdown()
        for c in (c0, c1):
            c.close()
        hub.close()


def _tcp_worker(rank, size, port, results):
    core = CoordinationCore.tcp(rank, size, "127.0.0.1", port,
                                cycle_ms=0.2)
    core.submit(f"t", "f32:8:sum", OP_ALLREDUCE, 32)
    r = core.wait(10.0)
    results[rank] = (r.type, tuple(r.names)) if r else None
    core.shutdown()
    # drain until shutdown completes so ranks exit cleanly
    time.sleep(0.2)
    core.close()


def test_tcp_transport_two_processes():
    """Real multi-process negotiation over localhost TCP (the reference's
    'real gloo over loopback' test strategy, SURVEY.md §4)."""
    port = 29517
    # spawn, not fork: the test session has live jax/XLA threads and a
    # forked child can deadlock on inherited lock state.
    ctx = multiprocessing.get_context("spawn")
    mgr = ctx.Manager()
    results = mgr.dict()
    procs = [ctx.Process(target=_tcp_worker, args=(r, 2, port, results))
             for r in range(2)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=30)
        assert not p.is_alive(), "tcp worker hung"
        assert p.exitcode == 0
    assert results[0] == ("OK", ("t",))
    assert results[1] == ("OK", ("t",))


# ----------------------------------------------------- plan-epoch fast path
def _epoch_cores(k=3, cycle_ms=0.5, bypass="1"):
    """Loopback pair with the bypass knobs pinned (the native core reads
    them from env at construction)."""
    old = {n: os.environ.get(n)
           for n in ("HOROVOD_BYPASS", "HOROVOD_BYPASS_STABLE_CYCLES")}
    os.environ["HOROVOD_BYPASS"] = bypass
    os.environ["HOROVOD_BYPASS_STABLE_CYCLES"] = str(k)
    try:
        hub = LoopbackHub(2)
        cores = [CoordinationCore.loopback(hub, r, cycle_ms=cycle_ms)
                 for r in range(2)]
    finally:
        for n, v in old.items():
            if v is None:
                os.environ.pop(n, None)
            else:
                os.environ[n] = v
    return hub, cores


def _epoch_step(cores, names, sig="f32:64:sum", nbytes=256, timeout=5.0):
    """One steady step: every core submits the set, drains it, and the
    per-core response batch sequence is returned for exactness checks."""
    for c in cores:
        for n in names:
            c.submit(n, sig, OP_ALLREDUCE, nbytes)
    seqs = []
    for c in cores:
        got, batches = [], []
        deadline = time.time() + timeout
        while len(got) < len(names) and time.time() < deadline:
            r = c.poll()
            if r:
                assert r.type == "OK", r
                batches.append((tuple(r.names), tuple(r.sigs)))
                got.extend(r.names)
            time.sleep(0.002)
        assert sorted(got) == sorted(names), got
        seqs.append(tuple(batches))
    return seqs


def _drive_to_lock(cores, names, steps=20, **kw):
    """Steady steps with idle gaps until rank 0 reports an epoch lock."""
    for _ in range(steps):
        _epoch_step(cores, names, **kw)
        time.sleep(0.01)  # idle cycles close the burst
        if cores[0].metrics()["counters"]["epoch_locks"] >= 1:
            return True
    return False


def _teardown(hub, cores):
    for c in cores:
        c.shutdown()
    for c in cores:
        c.close()
    hub.close()


def test_epoch_lock_zero_transport_and_counters():
    """After K identical steps the epoch locks; locked steps move ZERO
    coordination bytes and ZERO controller cycles — only the bypass
    counters advance (the tentpole claim, measured)."""
    hub, cores = _epoch_cores(k=3)
    try:
        names = [f"g{i}" for i in range(5)]
        assert _drive_to_lock(cores, names), \
            cores[0].metrics()["counters"]
        c = cores[0].metrics()["counters"]
        b0 = c["bytes_gathered"] + c["bytes_broadcast"]
        cyc0, byp0 = c["cycles"], c["bypass_cycles"]
        for _ in range(8):
            _epoch_step(cores, names)
        for core in cores:
            c1 = core.metrics()["counters"]
            assert c1["bytes_gathered"] + c1["bytes_broadcast"] == b0 \
                if core is cores[0] else True
            assert c1["epoch_locks"] == 1, c1
        c1 = cores[0].metrics()["counters"]
        assert c1["cycles"] == cyc0, (cyc0, c1["cycles"])
        assert c1["bypass_cycles"] >= byp0 + 8, c1
        assert c1["epoch_invalidations"] == 0, c1
    finally:
        _teardown(hub, cores)


def test_epoch_bypass_responses_bit_exact_vs_negotiated():
    """Replayed responses are BIT-EXACT the negotiated steady step's:
    same batches, same order, same names and signatures, on every rank."""
    hub, cores = _epoch_cores(k=4)
    try:
        names = [f"layer{i}/grad" for i in range(6)]
        # negotiated phase: record the steady step's response sequence
        negotiated = None
        for _ in range(3):
            seqs = _epoch_step(cores, names)
            time.sleep(0.01)
            assert seqs[0] == seqs[1], "ranks disagreed pre-lock"
            negotiated = seqs[0]
        assert _drive_to_lock(cores, names)
        locked = cores[0].metrics()["counters"]["bypass_cycles"]
        for _ in range(5):
            seqs = _epoch_step(cores, names)
            assert seqs[0] == negotiated, (seqs[0], negotiated)
            assert seqs[1] == negotiated, (seqs[1], negotiated)
        assert cores[0].metrics()["counters"]["bypass_cycles"] > locked
    finally:
        _teardown(hub, cores)


def test_epoch_break_on_new_tensor_falls_back_and_relocks():
    """A tensor outside the locked set breaks the epoch, renegotiates
    through the full path, and the workload can re-lock afterwards."""
    hub, cores = _epoch_cores(k=2)
    try:
        names = ["a", "b"]
        assert _drive_to_lock(cores, names)
        for c in cores:
            c.submit("newcomer", "f32:8:sum", OP_ALLREDUCE, 32)
        for c in cores:
            r = c.wait(5.0)
            assert r is not None and r.type == "OK", r
            assert r.names == ["newcomer"], r
        c0 = cores[0].metrics()["counters"]
        assert c0["epoch_invalidations"] >= 1, c0
        # the grown steady set stabilizes and locks again
        grown = names + ["newcomer"]
        for _ in range(30):
            _epoch_step(cores, grown)
            time.sleep(0.01)
            if cores[0].metrics()["counters"]["epoch_locks"] >= 2:
                break
        assert cores[0].metrics()["counters"]["epoch_locks"] >= 2
    finally:
        _teardown(hub, cores)


def test_epoch_break_on_signature_change():
    """A locked-set name resubmitted with a NEW signature must break the
    epoch and renegotiate — the new shape wins, exactly like the
    bit-vector cache invalidation underneath."""
    hub, cores = _epoch_cores(k=2)
    try:
        assert _drive_to_lock(cores, ["t"], sig="f32:4:sum", nbytes=16)
        for c in cores:
            c.submit("t", "f32:8:sum", OP_ALLREDUCE, 32)
        for c in cores:
            r = c.wait(5.0)
            assert r is not None and r.type == "OK", r
            assert r.sigs == ["f32:8:sum"], r
        assert cores[0].metrics()["counters"]["epoch_invalidations"] >= 1
    finally:
        _teardown(hub, cores)


def test_epoch_break_on_join():
    """JOIN while locked breaks the epoch; the join protocol then runs
    on the full path (joined rank auto-agrees, JOIN_DONE on all-join)."""
    hub, cores = _epoch_cores(k=2)
    try:
        c0, c1 = cores
        assert _drive_to_lock(cores, ["g"])
        c1.join()
        c0.submit("g", "f32:64:sum", OP_ALLREDUCE, 256)
        r = c0.wait(5.0)
        assert r is not None and r.type == "OK" and r.names == ["g"], r
        c0.join()
        r = c0.wait(5.0)
        assert r is not None and r.type == "JOIN_DONE", r
    finally:
        _teardown(hub, cores)


def test_epoch_partial_round_timeout_breaks_and_recovers():
    """A replay round left partial past the break window (a tensor of
    the locked set went missing) falls back to full negotiation: the
    already-submitted member re-materializes via carry and completes."""
    hub, cores = _epoch_cores(k=2)
    try:
        names = ["a", "b"]
        assert _drive_to_lock(cores, names)
        # Both ranks submit only 'a': the round can never complete.
        for c in cores:
            c.submit("a", "f32:64:sum", OP_ALLREDUCE, 256)
        r0 = cores[0].wait(10.0)   # arrives after the ~1 s break window
        r1 = cores[1].wait(10.0)
        assert r0 is not None and r0.names == ["a"], r0
        assert r1 is not None and r1.names == ["a"], r1
        c = cores[0].metrics()["counters"]
        assert c["epoch_invalidations"] >= 1, c
    finally:
        _teardown(hub, cores)


def test_bypass_disabled_by_knob():
    """HOROVOD_BYPASS=0: the bit-vector cache still serves steady steps
    but no epoch ever locks and every step keeps its transport cycles."""
    hub, cores = _epoch_cores(k=1, bypass="0")
    try:
        names = ["x", "y"]
        for _ in range(10):
            _epoch_step(cores, names)
            time.sleep(0.005)
        c = cores[0].metrics()["counters"]
        assert c["epoch_locks"] == 0, c
        assert c["bypass_cycles"] == 0, c
        assert c["cache_hits"] > 0, c  # the layer below still works
    finally:
        _teardown(hub, cores)


def test_epoch_trace_events():
    """Trace-plane coverage: epoch.lock / epoch.invalidate instants and
    cycle.bypass B/E spans land in the native ring (drained via
    hvd_core_trace) so the merged timeline shows the fast path."""
    hub, cores = _epoch_cores(k=2)
    try:
        for c in cores:
            c.trace_enable()
        names = ["t0", "t1"]
        assert _drive_to_lock(cores, names)
        for _ in range(3):
            _epoch_step(cores, names)
        for c in cores:
            c.submit("breaker", "f32:8:sum", OP_ALLREDUCE, 32)
        for c in cores:
            assert c.wait(5.0) is not None
        d = cores[0].trace_drain()
        kinds = {(e[1], e[3]) for e in d["events"]}
        assert ("i", "epoch.lock") in kinds, sorted(kinds)
        assert ("i", "epoch.invalidate") in kinds, sorted(kinds)
        assert ("B", "cycle.bypass") in kinds, sorted(kinds)
        assert ("E", "cycle.bypass") in kinds, sorted(kinds)
        # bypass spans carry the epoch (B) and the round size (E)
        ends = [e for e in d["events"]
                if e[1] == "E" and e[3] == "cycle.bypass"]
        assert any(e[4] == len(names) for e in ends), ends
    finally:
        _teardown(hub, cores)
