"""Eager collective op tests.

Coverage model follows the reference's parallel tier: every op x dtype x
fusion/grouping/prescale permutations with numerical checks (reference:
test/parallel/test_torch.py, test_tensorflow.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

def _per_chip(hvd, shape, dtype, seed=0):
    n = hvd.local_size()
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.integer):
        x = rng.randint(-10, 10, size=(n,) + shape).astype(dtype)
    else:
        x = rng.randn(*((n,) + shape)).astype(dtype)
    return x


@pytest.mark.parametrize("dtype", [np.float32, np.int32])
@pytest.mark.parametrize("shape", [(4,), (3, 5), (2, 3, 4)])
def test_allreduce_sum(hvd, dtype, shape):
    x = _per_chip(hvd, shape, dtype)
    out = np.asarray(hvd.allreduce(x, op=hvd.Sum))
    expected = np.broadcast_to(x.sum(axis=0), x.shape)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_allreduce_average(hvd):
    x = _per_chip(hvd, (16,), np.float32)
    out = np.asarray(hvd.allreduce(x, op=hvd.Average))
    np.testing.assert_allclose(out[0], x.mean(axis=0), rtol=1e-5)


def test_allreduce_average_flag(hvd):
    x = _per_chip(hvd, (8,), np.float32)
    out = np.asarray(hvd.allreduce(x, average=True))
    np.testing.assert_allclose(out[0], x.mean(axis=0), rtol=1e-5)


def test_allreduce_min_max(hvd):
    x = _per_chip(hvd, (7,), np.float32)
    mn = np.asarray(hvd.allreduce(x, op=hvd.Min))
    mx = np.asarray(hvd.allreduce(x, op=hvd.Max))
    np.testing.assert_allclose(mn[0], x.min(axis=0))
    np.testing.assert_allclose(mx[0], x.max(axis=0))


def test_allreduce_product(hvd):
    x = np.full((hvd.local_size(), 3), 2.0, np.float32)
    out = np.asarray(hvd.allreduce(x, op=hvd.Product))
    np.testing.assert_allclose(out[0], np.full(3, 2.0 ** hvd.size()))


def test_allreduce_prescale_postscale(hvd):
    """Pre/postscale factors (reference: operations.cc:948-1056)."""
    x = _per_chip(hvd, (5,), np.float32)
    out = np.asarray(hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                                   postscale_factor=0.5))
    np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-5)


def test_allreduce_replicated_input(hvd):
    """A tensor without a chip axis = every chip holds the same value."""
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = np.asarray(hvd.allreduce(x, op=hvd.Sum))
    np.testing.assert_allclose(out, x * hvd.size())


def test_allreduce_bfloat16(hvd):
    x = jnp.asarray(_per_chip(hvd, (8,), np.float32)).astype(jnp.bfloat16)
    out = hvd.allreduce(x, op=hvd.Sum)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out[0], np.float32),
        np.asarray(x, np.float32).sum(axis=0), rtol=2e-2)


def test_grouped_allreduce(hvd):
    """Fused multi-tensor reduce (reference: grouped_allreduce,
    operations.cc:919-1056)."""
    n = hvd.local_size()
    xs = [_per_chip(hvd, (k + 1,), np.float32, seed=k) for k in range(5)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o)[0], x.sum(axis=0),
                                   rtol=1e-5)


def test_grouped_allreduce_mixed_dtypes(hvd):
    xs = [_per_chip(hvd, (4,), np.float32),
          _per_chip(hvd, (6,), np.int32),
          _per_chip(hvd, (3,), np.float32, seed=7)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(o)[0], x.sum(axis=0),
                                   rtol=1e-5)


def test_allgather(hvd):
    x = _per_chip(hvd, (2, 3), np.float32)
    out = np.asarray(hvd.allgather(x))
    assert out.shape == (hvd.size() * 2, 3)
    np.testing.assert_allclose(out, x.reshape(-1, 3))


def test_allgather_ragged(hvd):
    """Ragged first dims (reference: controller.cc:580-650 size exchange)."""
    n = hvd.local_size()
    ts = [np.full((i + 1, 2), i, np.float32) for i in range(n)]
    out = np.asarray(hvd.allgather_ragged(ts))
    expected = np.concatenate(ts, axis=0)
    np.testing.assert_allclose(out, expected)


def test_broadcast(hvd):
    n = hvd.local_size()
    x = _per_chip(hvd, (4,), np.float32)
    for root in (0, 3, n - 1):
        out = np.asarray(hvd.broadcast(x, root_rank=root))
        np.testing.assert_allclose(out,
                                   np.broadcast_to(x[root], x.shape))


def test_broadcast_int(hvd):
    x = _per_chip(hvd, (5,), np.int32)
    out = np.asarray(hvd.broadcast(x, root_rank=2))
    np.testing.assert_allclose(out, np.broadcast_to(x[2], x.shape))


def test_alltoall_equal(hvd):
    n = hvd.size()
    # chip i sends block j to chip j; block value encodes (src, dst)
    x = np.zeros((n, n, 2), np.float32)
    for i in range(n):
        for j in range(n):
            x[i, j] = (i, j)
    out, recv = hvd.alltoall(x)
    out = np.asarray(out)
    assert np.all(np.asarray(recv) == 1)
    for i in range(n):
        for j in range(n):
            np.testing.assert_allclose(out[i, j], (j, i))


def test_alltoall_splits(hvd):
    """Uneven splits (reference: operations.cc:1136-1198 splits
    validation; torch/mpi_ops.py:759-841 returns recv splits)."""
    n = hvd.size()
    splits = np.zeros((n, n), np.int64)
    for i in range(n):
        for j in range(n):
            splits[i, j] = (i + j) % 3
    rows = splits.sum(axis=1)
    xs = np.zeros((n, int(rows.max()), 1), np.float32)
    data = []
    for i in range(n):
        vals = np.arange(rows[i], dtype=np.float32)[:, None] + 100 * i
        xs[i, :rows[i]] = vals
        data.append(vals)
    out, recv = hvd.alltoall(xs[:, :int(rows.max())], splits=splits)
    recv = np.asarray(recv)
    for i in range(n):
        np.testing.assert_allclose(recv[i], splits[:, i])
    # verify contents: chip d receives from src s the s-th block
    for d in range(n):
        o = out[d] if isinstance(out, list) else np.asarray(out)[d]
        off = 0
        for s in range(n):
            c = int(splits[s, d])
            src_off = int(splits[s, :d].sum())
            expected = data[s][src_off:src_off + c]
            got = np.asarray(o)[off:off + c]
            np.testing.assert_allclose(got, expected)
            off += c


def test_reducescatter(hvd):
    n = hvd.size()
    x = _per_chip(hvd, (n * 2, 3), np.float32)
    out = np.asarray(hvd.reducescatter(x, op=hvd.Sum))
    full = x.sum(axis=0)
    for i in range(n):
        np.testing.assert_allclose(out[i], full[i * 2:(i + 1) * 2],
                                   rtol=1e-5)


def test_barrier(hvd):
    hvd.barrier()  # must not hang or raise


def test_async_handles(hvd):
    """Async handle API (reference: torch/mpi_ops.py:843-881)."""
    x = _per_chip(hvd, (4,), np.float32)
    h = hvd.allreduce_async(x, op=hvd.Sum)
    out = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(axis=0), rtol=1e-5)


def test_plan_cache_hits(hvd):
    """Repeat grouped ops hit the bucket-plan cache (the response-cache
    analog, reference: response_cache.h:44-100)."""
    import horovod_tpu.runtime as rt
    cache = rt.get().plan_cache
    xs = [_per_chip(hvd, (3,), np.float32, seed=11)]
    hvd.grouped_allreduce(xs, op=hvd.Sum)
    before = cache.hits
    hvd.grouped_allreduce(xs, op=hvd.Sum)
    assert cache.hits > before
