"""Postmortem plane, fast tier (docs/postmortem.md):

  * native flight recorder — explicit-dump round trip, a REAL simulated
    fatal signal in a subprocess leaving a parseable record with native
    spans, torn-record tolerance, the lock-free health snapshot;
  * heartbeats — payload shape, the /health route's staleness semantics
    (server receipt time, tunable patience), publisher round trip;
  * supervision — HealthMonitor's heartbeat-lost and stall verdicts,
    including the pending-collectives attribution rule;
  * forensics — exit taxonomy, suspect classification precedence,
    build_postmortem assembly, and the `hvdrun doctor` rendering golden.

The 2-process kill/stall attribution experiments live in
tests/integration/test_postmortem_integration.py.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from horovod_tpu import postmortem as PM
from horovod_tpu.common.basics import (CoordinationCore, LoopbackHub,
                                       OP_ALLREDUCE)
from horovod_tpu.utils import health as H

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASICS = os.path.join(REPO, "horovod_tpu", "common", "basics.py")


@pytest.fixture
def loopback_core():
    hub = LoopbackHub(1)
    core = CoordinationCore.loopback(hub, rank=0)
    yield core
    core.shutdown()
    core.close()
    hub.close()


# ------------------------------------------------------------ flight record
def _negotiate_one(core):
    core.submit("t0", "f32:4", OP_ALLREDUCE, 16)
    assert core.wait(5.0) is not None


def test_flight_dump_round_trip(tmp_path, loopback_core):
    """Explicit dump -> parse: header, health, metrics, native cycle
    spans and the completion marker all survive the trip."""
    path = str(tmp_path / "flight.0")
    loopback_core.flight_enable(path)  # arms the ring
    _negotiate_one(loopback_core)
    assert loopback_core.flight_dump(path, "round-trip")
    fr = PM.parse_flight_record(path)
    assert fr["version"] == 1
    assert fr["reason"] == "explicit:round-trip"
    assert fr["rank"] == 0 and fr["size"] == 1
    assert fr["complete"] is True
    assert fr["health"]["transport_healthy"] == 1
    assert fr["metrics"]["responses"] >= 1
    names = [e[3] for e in fr["trace"]]
    assert "cycle.negotiate" in names, names


def test_flight_record_written_on_fatal_signal(tmp_path):
    """The acceptance experiment: a simulated SIGSEGV leaves a parseable
    flight record containing native spans, and the process still dies
    with the signal status its supervisor expects."""
    path = str(tmp_path / "flight.sig")
    script = f"""
import importlib.util, os, signal
spec = importlib.util.spec_from_file_location("hvd_basics", {BASICS!r})
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
hub = m.LoopbackHub(1)
core = m.CoordinationCore.loopback(hub, rank=0)
core.flight_enable({path!r})
core.submit("t0", "f32:4", m.OP_ALLREDUCE, 16)
core.wait(5.0)
os.kill(os.getpid(), signal.SIGSEGV)
"""
    res = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == -signal.SIGSEGV, (res.returncode, res.stderr)
    fr = PM.parse_flight_record(path)
    assert fr["reason"] == "signal:SIGSEGV"
    assert fr["complete"] is True
    assert any(e[3].startswith("cycle.") for e in fr["trace"]), fr["trace"]


def test_parse_flight_record_tolerates_torn_write(tmp_path, loopback_core):
    """A record truncated mid-crash parses with complete=False — torn
    evidence is partial evidence, never a parser error."""
    path = str(tmp_path / "flight.torn")
    loopback_core.flight_enable(path)
    _negotiate_one(loopback_core)
    assert loopback_core.flight_dump(path, "torn")
    text = open(path).read()
    cut = text[:text.index("[end]")].rstrip("\n")
    torn = cut[:-7]  # tear the final trace line too
    fr = PM.parse_flight_record(torn)
    assert fr["complete"] is False
    assert fr["reason"] == "explicit:torn"
    assert fr["health"]  # earlier sections intact


def test_parse_flight_record_rejects_non_record():
    with pytest.raises(ValueError, match="flight record"):
        PM.parse_flight_record("not a record\nat all\n")


def test_native_health_snapshot_is_live(loopback_core):
    h = loopback_core.health()
    assert h["version"] == 1
    assert h["transport_healthy"] == 1 and h["shutdown"] == 0
    cycles0 = h["cycles"]
    _negotiate_one(loopback_core)
    h2 = loopback_core.health()
    assert h2["cycles"] > cycles0
    assert h2["queue_depth"] == 0 and h2["responses_pending"] == 0
    # the progress stamp tracks the cycle loop, so its age stays far
    # below the 1 ms cycle time x a generous scheduling margin
    assert h2["last_progress_age_us"] < 5_000_000


# ---------------------------------------------------------------- heartbeats
def test_heartbeat_payload_carries_progress_and_core():
    H.reset_step()
    try:
        hb = H.heartbeat_payload(3)
        assert hb["rank"] == 3 and hb["step"] is None
        H.record_step(17)
        hb = H.heartbeat_payload(3, pending_collectives=2)
        assert hb["step"] == 17
        assert abs(hb["step_time"] - time.time()) < 5.0
        assert hb["pending_collectives"] == 2

        class _Clock:
            offset = 100.0
        hb_aligned = H.heartbeat_payload(3, clock=_Clock())
        assert hb_aligned["time"] - hb["time"] > 90.0  # offset applied
    finally:
        H.reset_step()


def test_health_route_staleness_semantics():
    """GET /health: fresh heartbeat -> stale False with a small age;
    the same heartbeat under ?stale_after=0 -> stale True.  Staleness
    judges the SERVER's receipt time, so a worker with a broken clock
    still ages honestly."""
    from horovod_tpu.runner.http_server import RendezvousServer
    server = RendezvousServer(host="127.0.0.1")
    port = server.start()
    try:
        H.record_step(5)
        pub = H.HeartbeatPublisher(
            "127.0.0.1", port, rank=0,
            payload_fn=lambda: H.heartbeat_payload(0))
        assert pub.publish_now()
        pub.close()

        def get(url):
            with urllib.request.urlopen(url, timeout=5) as r:
                return json.loads(r.read())

        view = get(f"http://127.0.0.1:{port}/health")
        info = view["ranks"]["0"]
        assert info["stale"] is False and info["age_s"] < 5.0
        assert info["heartbeat"]["step"] == 5

        impatient = get(f"http://127.0.0.1:{port}/health?stale_after=0")
        assert impatient["ranks"]["0"]["stale"] is True
        assert impatient["stale_after_s"] == 0.0
    finally:
        H.reset_step()
        server.stop()


def test_fleet_health_tolerates_torn_put():
    view = H.fleet_health({"rank.0": b"{not json", "junk": b"{}"},
                          {"rank.0": time.time()})
    assert view["ranks"] == {}  # torn PUT skipped, junk key ignored


# ---------------------------------------------------------------- monitor
def _view(now, ranks):
    return {"now": now, "stale_after_s": 10.0, "ranks": ranks}


def test_monitor_heartbeat_lost():
    view = _view(100.0, {"1": {"age_s": 20.0, "stale": True,
                               "heartbeat": {"time": 80.0}}})
    mon = H.HealthMonitor(lambda: view, timeout=5.0)
    assert mon.verdicts([1]) == {1: "heartbeat-lost"}
    # a rank that never heartbeated is bring-up, not a loss
    assert mon.verdicts([0, 1]) == {1: "heartbeat-lost"}


def test_monitor_stall_attributes_idle_rank():
    """Fleet-wide freeze: the rank with pending_collectives == 0 is the
    one that stopped feeding; the peers blocked INSIDE a collective are
    victims, not suspects."""
    view = _view(100.0, {
        "0": {"age_s": 0.3, "heartbeat": {"step_time": 90.0,
                                          "pending_collectives": 1}},
        "1": {"age_s": 0.3, "heartbeat": {"step_time": 90.5,
                                          "pending_collectives": 0}},
    })
    mon = H.HealthMonitor(lambda: view, timeout=5.0)
    assert mon.verdicts([0, 1]) == {1: "stall"}


def test_monitor_stall_whole_fleet_blocked_names_oldest():
    view = _view(100.0, {
        "0": {"age_s": 0.3, "heartbeat": {"step_time": 88.0,
                                          "pending_collectives": 1}},
        "1": {"age_s": 0.3, "heartbeat": {"step_time": 91.0,
                                          "pending_collectives": 2}},
    })
    mon = H.HealthMonitor(lambda: view, timeout=5.0)
    assert mon.verdicts([0, 1]) == {0: "stall"}
    # a PARTIAL freeze with every frozen rank blocked points at a peer
    # that already exited — no verdict
    assert mon.verdicts([0, 1, 2]) == {}


def test_monitor_healthy_fleet_no_verdicts():
    view = _view(100.0, {
        "0": {"age_s": 0.3, "heartbeat": {"step_time": 99.0,
                                          "pending_collectives": 0}},
    })
    mon = H.HealthMonitor(lambda: view, timeout=5.0)
    assert mon.verdicts([0]) == {}


# ------------------------------------------------------------ exit taxonomy
def test_classify_exit():
    assert PM.classify_exit(0) == "clean"
    assert PM.classify_exit(1) == "error:1"
    assert PM.classify_exit(-signal.SIGKILL) == "signal:SIGKILL"
    assert PM.classify_exit(-signal.SIGABRT) == "signal:SIGABRT"
    assert PM.classify_exit(PM.STALL_SHUTDOWN_EXIT) == "stall"
    assert PM.classify_exit(1, by_launcher=True) == "terminated"
    # the supervision verdict wins over the SIGABRT it was enforced with
    assert PM.classify_exit(-signal.SIGABRT,
                            supervision_cause="stall") == "stall"
    assert PM.classify_exit(None) == "unknown"


def test_classify_suspect_precedence():
    def info(cls="error:1", tail="", fr=None, met=None):
        return {"exit": {"classification": cls}, "log_tail": tail,
                "flight_record": fr, "metrics": met}

    assert PM.classify_suspect(
        info(tail="chaos: crashing rank 0 at fastcommit.pre_marker")
    )[0] == "torn_commit"
    assert PM.classify_suspect(
        info(tail="urllib.error.URLError: chaos: injected KV blackout")
    )[0] == "kv_blackout"
    assert PM.classify_suspect(info(cls="stall"))[0] == "stall"
    assert PM.classify_suspect(info(cls="heartbeat-lost"))[0] == "stall"
    assert PM.classify_suspect(
        info(fr={"metrics": {"transport_reconnect_failures": 2},
                 "health": {}}))[0] == "transport"
    assert PM.classify_suspect(
        info(tail="controller transport failure (peer died?)")
    )[0] == "transport"
    assert PM.classify_suspect(
        info(tail="chaos: killing rank 1 at step 2"))[0] == "kill"
    assert PM.classify_suspect(info(cls="signal:SIGKILL"))[0] == "kill"
    assert PM.classify_suspect(
        info(met={"chaos_injections": {"kill": 1}}))[0] == "kill"
    assert PM.classify_suspect(info())[0] == "unknown"


# ---------------------------------------------------------------- builder
def _sample_pm():
    exits = {
        0: {"rc": -signal.SIGTERM, "time": 1000.9, "by_launcher": True},
        1: {"rc": 1, "time": 1000.5},
    }
    health_view = _view(1000.9, {
        "1": {"age_s": 0.4, "heartbeat": {
            "rank": 1, "time": 1000.2, "step": 2, "step_time": 1000.1,
            "core": {"now_us": 700_000}}},
    })
    flights = {1: {"version": 1, "reason": "signal:SIGABRT", "rank": 1,
                   "complete": True, "health": {"cycles": 9},
                   "metrics": {},
                   "trace": [(600_000, "i", "t", "tcp.gather.send", 33)]}}
    return PM.build_postmortem(
        job={"np": 2, "command": ["python", "train.py"]},
        exits=exits, health_view=health_view, flight_records=flights,
        log_tails={1: "chaos: killing rank 1 at step 2\n"})


def test_build_postmortem_attributes_first_failure():
    pm = _sample_pm()
    assert pm["schema"] == PM.SCHEMA
    assert pm["first_failure"]["rank"] == 1
    assert pm["first_failure"]["classification"] == "error:1"
    assert pm["suspect"] == {
        "rank": 1, "classification": "kill",
        "evidence": ["exit classification error:1",
                     "chaos injector logged the kill"]}
    # rank 0 died at the launcher's hand: collateral, not a failure
    assert pm["ranks"]["0"]["exit"]["classification"] == "terminated"
    # events ride the fleet clock, sorted; the flight span was anchored
    # via the heartbeat (epoch = hb.time - core.now_us/1e6 -> t=1000.1)
    ts = [e["t"] for e in pm["events"]]
    assert ts == sorted(ts) and len(ts) >= 4
    span = next(e for e in pm["events"] if e["kind"] == "span")
    assert span["name"] == "tcp.gather.send"
    assert abs(span["t"] - 1000.1) < 1e-6


def test_postmortem_json_round_trip(tmp_path):
    pm = _sample_pm()
    path = PM.write_postmortem(pm, str(tmp_path / "postmortem.json"))
    # load accepts the file AND the directory holding it
    assert PM.load_postmortem(path)["suspect"]["rank"] == 1
    assert PM.load_postmortem(str(tmp_path))["suspect"]["rank"] == 1
    with pytest.raises(ValueError, match="schema"):
        bad = str(tmp_path / "bad")
        os.mkdir(bad)
        with open(os.path.join(bad, "postmortem.json"), "w") as f:
            json.dump({"schema": "nope"}, f)
        PM.load_postmortem(bad)


# ------------------------------------------------------------------ doctor
def test_doctor_rendering_golden():
    """Root-cause-first contract: the first line a reader sees names the
    failing rank and classification; taxonomy, fleet-clock events and
    per-rank forensics follow."""
    from horovod_tpu.runner.doctor import render
    out = render(_sample_pm())
    lines = out.splitlines()
    assert lines[0].startswith("== hvdrun doctor: postmortem of "
                               "`python train.py` (np=2)")
    assert lines[1].startswith("ROOT CAUSE: rank 1 — kill "
                               "(first failure error:1")
    assert "  evidence: chaos injector logged the kill" in lines
    assert "  rank 0: terminated (rc=-15)" in lines
    assert "  rank 1: error:1 (rc=1, last step 2)" in lines
    assert any("Last events (fleet clock" in ln for ln in lines)
    assert any("span: tcp.gather.send" in ln for ln in lines)
    assert "-- rank 1 forensics --" in lines
    assert any("flight record: reason=signal:SIGABRT complete=True "
               "spans=1" in ln for ln in lines)
    assert any("| chaos: killing rank 1 at step 2" in ln for ln in lines)


def test_doctor_cli_renders_and_rejects(tmp_path, capsys):
    from horovod_tpu.runner.doctor import main
    PM.write_postmortem(_sample_pm(), str(tmp_path / "postmortem.json"))
    assert main([str(tmp_path)]) == 0
    assert "ROOT CAUSE: rank 1" in capsys.readouterr().out
    assert main([str(tmp_path), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["suspect"]["rank"] == 1
    assert main([str(tmp_path / "missing.json")]) == 2


def test_hvdrun_doctor_subcommand_dispatch(tmp_path, capsys):
    """`hvdrun doctor <dir>` routes to the doctor before the launcher's
    parser (which would otherwise demand -np and a command)."""
    from horovod_tpu.runner.launch import run_commandline
    PM.write_postmortem(_sample_pm(), str(tmp_path / "postmortem.json"))
    assert run_commandline(["doctor", str(tmp_path)]) == 0
    assert "ROOT CAUSE: rank 1" in capsys.readouterr().out
