"""The profile analyzer (scripts/analyze_profile.py) against a real
jax.profiler capture — the XLA-level observability tool beside the
Horovod-style timeline (reference perf story: timeline.{h,cc} + NVTX
ranges; here the device-truth comes from the jax profiler)."""

import importlib.util
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "analyze_profile",
        os.path.join(REPO, "scripts", "analyze_profile.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("prof"))
    x = jnp.asarray(np.random.RandomState(0).randn(256, 256), jnp.float32)
    f = jax.jit(lambda x: jnp.tanh(x @ x) @ x)
    f(x).block_until_ready()  # compile outside the capture
    with jax.profiler.trace(d):
        r = f(x)
        for _ in range(4):
            r = f(r)
        r.block_until_ready()
    return d


def test_finds_and_aggregates_device_ops(trace_dir):
    ap = _load()
    trace_file = ap.find_trace(trace_dir)
    events, pid_names, tid_names = ap.load_events(trace_file)
    pids = ap.device_pids(pid_names)
    assert pids
    per_op, busy_us, span_us = ap.summarize(
        events, pids, ap.op_tids(events, pids, tid_names))
    assert busy_us > 0 and span_us > 0
    # the jitted program is two matmuls + tanh: both a dot op and the
    # tanh must be found and categorized.  Which of the two WINS on
    # total time is a CPU-thread-scheduling outcome, not a property of
    # the analyzer — under a loaded full-suite run the 5 tanh
    # dispatches can out-time the 256x256 dots (observed: tanh.3 at
    # 154 µs > the dots) — so the top op is only asserted to be one of
    # the program's compute ops, never a runtime/envelope frame.
    names = " ".join(per_op)
    assert "dot" in names, names
    dots = {n: v for n, v in per_op.items()
            if ap.categorize(n) == "matmul/conv"}
    assert dots and all(us > 0 for us, _ in dots.values()), per_op
    top = max(per_op.items(), key=lambda kv: kv[1][0])
    assert ap.categorize(top[0]) in ("matmul/conv",
                                     "elementwise/fusion"), top
    # python-frame / runtime-dispatch / envelope events are excluded
    for n in per_op:
        assert not n.startswith(("$", "end: ", "PjitFunction", "PjRt",
                                 "ThreadpoolListener")), n


def test_categorize_tpu_op_names():
    ap = _load()
    assert ap.categorize("fusion.123") == "elementwise/fusion"
    assert ap.categorize("all-reduce.7") == "collective"
    assert ap.categorize("custom-call _attn_kernel") == "pallas/custom"
    assert ap.categorize("copy-start.2") == "data-movement"
    assert ap.categorize("rng-bit-generator") == "other"


def test_cli_end_to_end(trace_dir, tmp_path):
    csv = str(tmp_path / "ops.csv")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze_profile.py"),
         trace_dir, "--top", "5", "--csv", csv],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "by category:" in proc.stdout and "top" in proc.stdout
    with open(csv) as f:
        header = f.readline().strip()
    assert header == "op,category,total_ms,count"
