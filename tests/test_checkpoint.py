"""Sharded checkpointing (orbax-backed): save/restore distributed pytrees
with shardings preserved, retention, and the elastic JaxState integration
(reference conventions being upgraded: SURVEY.md §5 checkpoint/resume)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.checkpoint import (CheckpointManager, restore_checkpoint,
                                    save_checkpoint)


@pytest.fixture
def sharded_state(hvd):
    mesh = hvd.mesh()
    shard = NamedSharding(mesh, P("hvd"))
    repl = NamedSharding(mesh, P())
    params = {
        "w": jax.device_put(jnp.arange(32.0).reshape(8, 4), shard),
        "b": jax.device_put(jnp.ones((4,)), repl),
    }
    opt_state = {"mu": jax.device_put(jnp.zeros((8, 4)) + 0.5, shard)}
    return mesh, params, opt_state


def test_save_restore_preserves_values_and_shardings(tmp_path,
                                                     sharded_state):
    mesh, params, opt_state = sharded_state
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.save(0, params=params, opt_state=opt_state,
                    meta={"epoch": 3})
    mgr.wait()

    # templates carry shapes+shardings; values are garbage to be replaced
    tmpl_p = jax.tree_util.tree_map(lambda x: x * 0 - 1, params)
    tmpl_o = jax.tree_util.tree_map(lambda x: x * 0 - 1, opt_state)
    out = mgr.restore(0, params=tmpl_p, opt_state=tmpl_o)
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.arange(32.0).reshape(8, 4))
    np.testing.assert_allclose(np.asarray(out["opt_state"]["mu"]), 0.5)
    assert out["meta"]["epoch"] == 3
    # restored array lands in the template's sharding
    assert out["params"]["w"].sharding.spec == P("hvd")
    assert out["params"]["b"].sharding.spec == P()
    mgr.close()


def test_latest_step_and_retention(tmp_path, sharded_state):
    _, params, _ = sharded_state
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for step in (0, 1, 2, 3):
        assert mgr.save(step, params=params, force=True)
    mgr.wait()
    assert mgr.latest_step() == 3
    assert mgr.all_steps() == [2, 3]  # max_to_keep=2 pruned older steps
    mgr.close()


def test_one_shot_helpers(tmp_path, sharded_state):
    _, params, _ = sharded_state
    save_checkpoint(str(tmp_path / "c"), 7, params=params,
                    meta={"note": "x"})
    out = restore_checkpoint(str(tmp_path / "c"), params=params)
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.asarray(params["w"]))
    assert out["meta"]["note"] == "x"


def test_restore_missing_raises(tmp_path, sharded_state):
    _, params, _ = sharded_state
    mgr = CheckpointManager(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        mgr.restore(params=params)
    mgr.close()


def test_jaxstate_sharded_commit_roundtrip(tmp_path, hvd, sharded_state):
    from horovod_tpu.elastic.state import JaxState
    mesh, params, opt_state = sharded_state
    state = JaxState(params=params, opt_state=opt_state,
                     sharded_commit_dir=str(tmp_path / "elastic"),
                     epoch=0, batch=0)
    state.epoch = 2
    state.commit()
    state.epoch = 5
    state.params = jax.tree_util.tree_map(lambda x: x + 100.0, state.params)
    state.commit()

    # a fresh incarnation (templates only) resumes from the LAST commit
    fresh = JaxState(params=jax.tree_util.tree_map(jnp.zeros_like, params),
                     opt_state=jax.tree_util.tree_map(jnp.zeros_like,
                                                      opt_state),
                     sharded_commit_dir=str(tmp_path / "elastic"),
                     epoch=0, batch=0)
    assert fresh.load_from_disk()
    assert fresh.epoch == 5
    np.testing.assert_allclose(
        np.asarray(fresh.params["w"]),
        np.arange(32.0).reshape(8, 4) + 100.0)


def test_meta_preserves_python_types(tmp_path, sharded_state):
    """meta must round-trip numpy scalars and tuples intact (regression:
    plain JSON narrowed or rejected them)."""
    _, params, _ = sharded_state
    mgr = CheckpointManager(str(tmp_path / "m"))
    mgr.save(0, params=params,
             meta={"epoch": np.int64(3), "shape": (4, 2), "lr": 1e-3})
    mgr.wait()
    out = mgr.restore(0, params=params)
    assert out["meta"]["epoch"] == 3
    assert isinstance(out["meta"]["epoch"], np.int64)
    assert out["meta"]["shape"] == (4, 2)
    mgr.close()


def test_restore_without_meta_payload(tmp_path, sharded_state):
    _, params, _ = sharded_state
    mgr = CheckpointManager(str(tmp_path / "nm"))
    mgr.save(0, params=params)  # no meta
    mgr.wait()
    out = mgr.restore(0, params=params)
    assert "meta" not in out
    np.testing.assert_allclose(np.asarray(out["params"]["w"]),
                               np.asarray(params["w"]))
    mgr.close()
