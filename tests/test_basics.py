"""Init/topology API tests (reference analog: test/parallel/test_torch.py
rank/size sanity via mpi_env_rank_and_size, test/utils/common.py:32-70)."""

import numpy as np


def test_init_idempotent(hvd):
    rt1 = hvd.init()
    rt2 = hvd.init()
    assert rt1 is rt2


def test_topology(hvd):
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.process_size() == 1
    assert hvd.process_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()


def test_built_flags(hvd):
    assert hvd.tpu_built() and hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.gloo_built()


def test_mesh_shape(hvd):
    assert hvd.mesh().devices.size == 8
    assert hvd.mesh().axis_names == ("hvd",)


def test_reduce_op_constants(hvd):
    assert int(hvd.Average) == 0
    assert int(hvd.Sum) == 1
    assert int(hvd.Adasum) == 2
