"""Init/topology API tests (reference analog: test/parallel/test_torch.py
rank/size sanity via mpi_env_rank_and_size, test/utils/common.py:32-70)."""

import os

import numpy as np


def test_init_idempotent(hvd):
    rt1 = hvd.init()
    rt2 = hvd.init()
    assert rt1 is rt2


def test_topology(hvd):
    assert hvd.size() == 8
    assert hvd.local_size() == 8
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.process_size() == 1
    assert hvd.process_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.is_homogeneous()


def test_built_flags(hvd):
    assert hvd.tpu_built() and hvd.xla_built()
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert not hvd.gloo_built()


def test_mesh_shape(hvd):
    assert hvd.mesh().devices.size == 8
    if os.environ.get("HOROVOD_LAYOUT"):
        # CI layout knob dim (docs/parallelism.md): init resolved the
        # 3-axis training mesh instead of the legacy single axis.
        assert hvd.mesh().axis_names == ("dp", "tp", "pp")
    else:
        assert hvd.mesh().axis_names == ("hvd",)


def test_reduce_op_constants(hvd):
    assert int(hvd.Average) == 0
    assert int(hvd.Sum) == 1
    assert int(hvd.Adasum) == 2


def test_frontend_api_parity_names():
    """Names the reference exports per frontend that users script
    against (reference: horovod/{torch,tensorflow,keras,mxnet,ray}
    __init__ public surfaces); an AST audit found these missing in r4 —
    pin them so they cannot regress.  Every frontend must also expose
    the WHOLE shared capability surface (hvd.CAPABILITY_EXPORTS), both
    as attributes and in __all__."""
    import importlib

    import pytest

    import horovod_tpu
    surface = {
        "horovod_tpu.torch": ("torch", ["check_extension"]),
        "horovod_tpu.keras": ("keras", []),
        "horovod_tpu.mxnet": (None, ["allgather_object",
                                     "broadcast_object",
                                     "check_extension"]),
        "horovod_tpu.ray": (None, ["BaseHorovodWorker"]),
    }
    for mod, (dep, names) in surface.items():
        if dep is not None:
            pytest.importorskip(dep)
        m = importlib.import_module(mod)
        if mod != "horovod_tpu.ray":  # ray surface has no probes in ref
            names = list(names) + list(horovod_tpu.CAPABILITY_EXPORTS)
            missing = [n for n in names if not hasattr(m, n)]
            not_exported = [n for n in horovod_tpu.CAPABILITY_EXPORTS
                            if n not in m.__all__]
            assert not not_exported, f"{mod} __all__ missing {not_exported}"
        else:
            missing = [n for n in names if not hasattr(m, n)]
        assert not missing, f"{mod} missing {missing}"
    # common.util semantics
    from horovod_tpu.common.util import (check_num_rank_power_of_2,
                                         split_list)
    assert check_num_rank_power_of_2(8) and \
        not check_num_rank_power_of_2(6)
    assert split_list(list(range(7)), 3) == [[0, 1, 2], [3, 4], [5, 6]]
