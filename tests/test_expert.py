"""Expert parallelism (parallel/expert.py): the all_to_all MoE data path
must equal the single-device reference with identical routing math,
gradients must flow, and capacity dropping must behave."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.expert import (init_moe_params, make_moe_fn,
                                         moe_dense_reference,
                                         moe_shardings)

EP = 4


def _mesh(hvd):
    return Mesh(np.array(jax.devices()[:EP]).reshape(EP), ("ep",))


def _sharded_reference(params, x, n_experts, capacity_factor, ep):
    """Per-shard dense reference: routing (incl. cumsum positions and
    capacity drops) happens within each chip's token shard, exactly as
    the distributed path does."""
    T = x.shape[0]
    t_local = T // ep
    capacity = int(np.ceil(t_local * capacity_factor / n_experts))
    ys, auxs = [], []
    for s in range(ep):
        y, aux = moe_dense_reference(params,
                                     x[s * t_local:(s + 1) * t_local],
                                     n_experts, capacity)
        ys.append(y)
        auxs.append(aux)
    return jnp.concatenate(ys), jnp.mean(jnp.stack(auxs))


def test_moe_matches_dense_reference(hvd):
    mesh = _mesh(hvd)
    E, D, H, T = 8, 16, 32, 64
    params = init_moe_params(jax.random.PRNGKey(0), D, H, E)
    x = jax.random.normal(jax.random.PRNGKey(1), (T, D))

    fn = make_moe_fn(mesh, n_experts=E, capacity_factor=2.0)
    y, aux = fn(params, x)
    y_ref, aux_ref = _sharded_reference(params, x, E, 2.0, EP)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_moe_capacity_drops_tokens(hvd):
    """With a tiny capacity factor some tokens must be dropped (output
    exactly zero), never silently mis-routed."""
    mesh = _mesh(hvd)
    E, D, H, T = 4, 8, 16, 32
    params = init_moe_params(jax.random.PRNGKey(2), D, H, E)
    x = jax.random.normal(jax.random.PRNGKey(3), (T, D))

    fn = make_moe_fn(mesh, n_experts=E, capacity_factor=0.5)
    y, _ = fn(params, x)
    y_ref, _ = _sharded_reference(params, x, E, 0.5, EP)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=2e-4, atol=2e-5)
    dropped = np.all(np.asarray(y) == 0.0, axis=-1)
    assert dropped.any()  # capacity 0.5 must drop something
    assert not dropped.all()


def test_moe_gradients_flow(hvd):
    mesh = _mesh(hvd)
    E, D, H, T = 4, 8, 16, 32
    params = init_moe_params(jax.random.PRNGKey(4), D, H, E)
    x = jax.random.normal(jax.random.PRNGKey(5), (T, D))
    tgt = jax.random.normal(jax.random.PRNGKey(6), (T, D))

    fn = make_moe_fn(mesh, n_experts=E, capacity_factor=2.0)

    def loss(p):
        y, aux = fn(p, x)
        return jnp.mean((y - tgt) ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("router", "wi", "wo"):
        assert np.isfinite(np.asarray(g[k])).all()
        assert float(jnp.abs(g[k]).sum()) > 0.0, k


def test_moe_train_step_converges(hvd):
    import optax
    mesh = _mesh(hvd)
    E, D, H, T = 4, 8, 16, 64
    params = init_moe_params(jax.random.PRNGKey(7), D, H, E)
    params = jax.device_put(params, moe_shardings(mesh, params))
    x = jax.random.normal(jax.random.PRNGKey(8), (T, D))
    tgt = jnp.tanh(x @ jax.random.normal(jax.random.PRNGKey(9), (D, D)))

    fn = make_moe_fn(mesh, n_experts=E, capacity_factor=2.0)
    opt = optax.adam(1e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        def loss(q):
            y, aux = fn(q, x)
            return jnp.mean((y - tgt) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss)(p)
        up, s = opt.update(g, s)
        return optax.apply_updates(p, up), s, l

    losses = []
    for _ in range(30):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_moe_rejects_indivisible_experts(hvd):
    mesh = _mesh(hvd)
    with pytest.raises(ValueError, match="not divisible"):
        make_moe_fn(mesh, n_experts=6)


# ------------------------------------------------------------ MoE model zoo
def test_moe_llama_trains_dense(hvd):
    """models/moe_llama: dense path trains (loss drops, aux finite)."""
    import optax
    from horovod_tpu.models import moe_llama

    cfg = moe_llama.CONFIGS["tiny"]
    params = moe_llama.init(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab, (4, 33)), jnp.int32)
    opt = optax.adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(
            lambda q: moe_llama.loss_fn(q, ids, cfg))(p)
        up, s = opt.update(g, s)
        import optax as _o
        return _o.apply_updates(p, up), s, l

    losses = []
    for _ in range(12):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_moe_llama_ep_path_matches_dense(hvd):
    """The SAME params through the expert-parallel moe_fn must produce
    the same logits as the dense path (per-shard routing; batch shaped so
    shards align)."""
    from horovod_tpu.models import moe_llama
    from horovod_tpu.parallel.expert import make_moe_fn

    cfg = moe_llama.CONFIGS["tiny"]
    mesh = _mesh(hvd)
    params = moe_llama.init(jax.random.PRNGKey(1), cfg)
    # B*S divisible by ep, and capacity factor high so that dense
    # (global routing) and EP (per-shard routing) drop nothing.
    ids = jnp.asarray(np.random.RandomState(1).randint(
        0, cfg.vocab, (4, 17)), jnp.int32)
    big = dataclasses_replace_cf(cfg, 8.0)
    fn = make_moe_fn(mesh, n_experts=cfg.n_experts, capacity_factor=8.0)
    logits_ep, _ = moe_llama.apply(params, ids[:, :-1], big, moe_fn=fn)
    logits_dense, _ = moe_llama.apply(params, ids[:, :-1], big)
    np.testing.assert_allclose(np.asarray(logits_ep),
                               np.asarray(logits_dense),
                               rtol=5e-4, atol=5e-5)


def dataclasses_replace_cf(cfg, cf):
    import dataclasses
    return dataclasses.replace(cfg, capacity_factor=cf)


def test_moe_llama_param_count(hvd):
    from horovod_tpu.models import moe_llama
    cfg = moe_llama.CONFIGS["tiny"]
    params = moe_llama.init(jax.random.PRNGKey(2), cfg)
    n = sum(int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(params))
    assert n == moe_llama.param_count(cfg), (n, moe_llama.param_count(cfg))


# -------------------------------------------------------------- top-k routing
def test_moe_top2_matches_dense_reference(hvd):
    mesh = _mesh(hvd)
    E, D, H, T = 8, 16, 32, 64
    params = init_moe_params(jax.random.PRNGKey(10), D, H, E)
    x = jax.random.normal(jax.random.PRNGKey(11), (T, D))

    fn = make_moe_fn(mesh, n_experts=E, capacity_factor=2.0,
                     experts_per_token=2)
    y, aux = fn(params, x)

    t_local = T // EP
    capacity = int(np.ceil(t_local * 2 * 2.0 / E))
    ys, auxs = [], []
    for s in range(EP):
        yy, aa = moe_dense_reference(params,
                                     x[s * t_local:(s + 1) * t_local],
                                     E, capacity, experts_per_token=2)
        ys.append(yy)
        auxs.append(aa)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(jnp.concatenate(ys)),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(aux),
                               float(jnp.mean(jnp.stack(auxs))), rtol=1e-5)


def test_moe_top2_equals_full_soft_mixture_when_k_is_E(hvd):
    """k = E = 2 with ample capacity: every token reaches BOTH experts and
    the renormalized top-2 gates are the full softmax — the MoE output
    must equal the dense soft mixture sum_e p_e * expert_e(x)."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("ep",))
    E, D, H, T = 2, 8, 16, 32
    params = init_moe_params(jax.random.PRNGKey(12), D, H, E)
    x = jax.random.normal(jax.random.PRNGKey(13), (T, D))

    fn = make_moe_fn(mesh, n_experts=E, capacity_factor=4.0,
                     experts_per_token=2)
    y, _ = fn(params, x)

    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    h = jax.nn.gelu(jnp.einsum("td,edh->teh", x, params["wi"]))
    full = jnp.einsum("teh,ehd->ted", h, params["wo"])
    soft = jnp.einsum("ted,te->td", full, probs)
    np.testing.assert_allclose(np.asarray(y), np.asarray(soft),
                               rtol=2e-4, atol=2e-5)


def test_moe_llama_mixtral_config_trains(hvd):
    import optax
    from horovod_tpu.models import moe_llama

    cfg = moe_llama.CONFIGS["mixtral-tiny"]
    assert cfg.experts_per_token == 2
    params = moe_llama.init(jax.random.PRNGKey(14), cfg)
    ids = jnp.asarray(np.random.RandomState(5).randint(
        0, cfg.vocab, (4, 33)), jnp.int32)
    opt = optax.adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(
            lambda q: moe_llama.loss_fn(q, ids, cfg))(p)
        up, s = opt.update(g, s)
        return optax.apply_updates(p, up), s, l

    losses = []
    for _ in range(10):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
