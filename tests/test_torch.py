"""Torch frontend tests.

Mirrors the reference's parallel torch suite strategy (reference:
test/parallel/test_torch.py, 2448 LoC): every op x dtype sweep, autograd
checks, optimizer convergence, broadcast of parameters/optimizer state,
sync-BN numerics, elastic state/sampler — on the 8-virtual-chip CPU mesh.
"""

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvd


@pytest.fixture(scope="module", autouse=True)
def _init(hvd_rt):
    yield


@pytest.fixture(scope="session")
def hvd_rt():
    import horovod_tpu
    horovod_tpu.init()
    return horovod_tpu


DTYPES = [torch.float32, torch.float64, torch.int32, torch.int64,
          torch.float16, torch.bfloat16]


# ------------------------------------------------------------------ allreduce
@pytest.mark.parametrize("dtype", DTYPES)
def test_allreduce_average_identity(dtype):
    # Every chip holds the same value -> Average returns it unchanged.
    t = (torch.arange(12).reshape(3, 4) % 5).to(dtype)
    out = hvd.allreduce(t, op=hvd.Average)
    assert out.dtype == dtype
    assert torch.allclose(out.float(), t.float(), atol=1e-3)


def test_allreduce_sum_scales_by_size():
    t = torch.ones(4, 2)
    out = hvd.allreduce(t, op=hvd.Sum)
    assert torch.allclose(out, t * hvd.size())


def test_allreduce_min_max_product():
    t = torch.full((3,), 2.0)
    assert torch.allclose(hvd.allreduce(t, op=hvd.Min), t)
    assert torch.allclose(hvd.allreduce(t, op=hvd.Max), t)
    assert torch.allclose(hvd.allreduce(t, op=hvd.Product),
                          t ** hvd.size())


def test_allreduce_average_deprecated_flag():
    t = torch.ones(3)
    assert torch.allclose(hvd.allreduce(t, average=True), t)
    assert torch.allclose(hvd.allreduce(t, average=False), t * hvd.size())
    with pytest.raises(ValueError):
        hvd.allreduce(t, average=True, op=hvd.Sum)


def test_allreduce_inplace_and_async():
    t = torch.ones(5)
    h = hvd.allreduce_async_(t, op=hvd.Sum, name="ar_async_ip")
    out = hvd.synchronize(h)
    assert out is t
    assert torch.allclose(t, torch.full((5,), float(hvd.size())))

    h2 = hvd.allreduce_async(torch.ones(2), op=hvd.Average)
    assert hvd.poll(h2) in (True, False)
    res = hvd.synchronize(h2)
    assert torch.allclose(res, torch.ones(2))


def test_allreduce_prescale_postscale():
    t = torch.ones(3)
    out = hvd.allreduce(t, op=hvd.Sum, prescale_factor=0.5)
    assert torch.allclose(out, t * hvd.size() * 0.5)
    out = hvd.allreduce(t, op=hvd.Sum, postscale_factor=2.0)
    assert torch.allclose(out, t * hvd.size() * 2.0)


def test_allreduce_grad():
    t = torch.ones(4, requires_grad=True)
    out = hvd.allreduce(t, op=hvd.Average)
    out.sum().backward()
    # Average backward: grad averaged over workers -> ones.
    assert torch.allclose(t.grad, torch.ones(4))


def test_allreduce_adasum_identity_on_replicated():
    # adasum(a, a) == a: identical vectors mix back to themselves.
    t = torch.randn(8)
    out = hvd.allreduce(t, op=hvd.Adasum)
    assert torch.allclose(out, t, atol=1e-5)


def test_grouped_allreduce():
    ts = [torch.ones(3), torch.full((2, 2), 2.0)]
    outs = hvd.grouped_allreduce(ts, op=hvd.Sum)
    assert torch.allclose(outs[0], ts[0] * hvd.size())
    assert torch.allclose(outs[1], ts[1] * hvd.size())
    # In-place variant
    ts2 = [torch.ones(3), torch.ones(4)]
    outs2 = hvd.grouped_allreduce_(ts2, op=hvd.Average)
    assert outs2[0] is ts2[0]
    assert torch.allclose(ts2[1], torch.ones(4))


def test_compression_fp16_roundtrip():
    t = torch.randn(16)
    out = hvd.allreduce(t, op=hvd.Average, compression=hvd.Compression.fp16)
    assert out.dtype == torch.float32
    assert torch.allclose(out, t, atol=1e-2)


# ------------------------------------------------------------------ allgather
def test_allgather_replicates_per_chip():
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd.allgather(t)
    assert out.shape == (2 * hvd.size(), 3)
    for i in range(hvd.size()):
        assert torch.allclose(out[2 * i:2 * i + 2], t)


def test_allgather_grad():
    t = torch.ones(2, requires_grad=True)
    out = hvd.allgather(t)
    out.sum().backward()
    # Sum-allreduced grad narrowed to own rows: each entry = size().
    assert torch.allclose(t.grad, torch.full((2,), float(hvd.size())))


def test_allgather_object():
    objs = hvd.allgather_object({"r": hvd.rank()})
    assert len(objs) == hvd.size()
    assert objs[0] == {"r": hvd.rank()}


# ------------------------------------------------------------------ broadcast
def test_broadcast_from_root():
    t = torch.randn(4)
    out = hvd.broadcast(t, root_rank=0)
    assert torch.allclose(out, t)
    t2 = torch.randn(3)
    hvd.broadcast_(t2, root_rank=0)


def test_broadcast_object():
    obj = {"a": [1, 2, 3], "b": "hello"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_broadcast_parameters_and_optimizer_state():
    model = torch.nn.Linear(4, 2)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    loss = model(torch.randn(3, 4)).sum()
    loss.backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)


# ------------------------------------------------------------------- alltoall
def test_alltoall_even():
    n = hvd.size()
    t = torch.arange(n * 2, dtype=torch.float32).reshape(n * 2, 1)
    out = hvd.alltoall(t)
    assert out.shape == (n * 2, 1)


def test_alltoall_splits():
    n = hvd.size()
    splits = torch.ones(n, dtype=torch.int64)
    t = torch.arange(n, dtype=torch.float32)
    out, recv = hvd.alltoall(t, splits=splits)
    assert int(recv.sum()) == out.shape[0]


# ------------------------------------------------------------------ optimizer
def _train(opt_factory, steps=30):
    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                                torch.nn.Linear(8, 1))
    opt = opt_factory(model)
    x = torch.randn(64, 4)
    w = torch.randn(4, 1)
    y = x @ w
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    return losses


def test_distributed_optimizer_converges():
    def make(model):
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        return hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
    losses = _train(make)
    assert losses[-1] < losses[0] * 0.5


def test_distributed_optimizer_matches_local():
    # With replicated data, distributed Average == local training exactly.
    def make_d(model):
        opt = torch.optim.SGD(model.parameters(), lr=0.05)
        return hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
    d_losses = _train(make_d, steps=10)
    l_losses = _train(lambda m: torch.optim.SGD(m.parameters(), lr=0.05),
                      steps=10)
    np.testing.assert_allclose(d_losses, l_losses, rtol=1e-4)


def test_distributed_optimizer_num_groups():
    def make(model):
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        return hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters(), num_groups=2)
    losses = _train(make)
    assert losses[-1] < losses[0] * 0.5


def test_distributed_optimizer_partial_groups_covers_rest():
    """Explicit groups covering only SOME parameters: uncovered params
    must reduce individually, not crash the grad hook."""
    def make(model):
        params = list(model.parameters())
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        return hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters(),
            groups=[params[:1]])  # everything else is ungrouped
    losses = _train(make)
    assert losses[-1] < losses[0] * 0.5


def test_distributed_optimizer_backward_passes_per_step():
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    x = torch.randn(8, 4)
    for _ in range(2):
        loss = model(x).sum()
        loss.backward()
    opt.step()
    opt.zero_grad()


def test_distributed_optimizer_zero_grad_guard():
    model = torch.nn.Linear(2, 1)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    model(torch.randn(3, 2)).sum().backward()
    with pytest.raises(AssertionError):
        opt.zero_grad()
    opt.step()  # clears handles


def test_distributed_optimizer_duplicate_names_rejected():
    model = torch.nn.Linear(2, 1)
    with pytest.raises(ValueError):
        hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("w", model.weight), ("w", model.bias)])


def test_adasum_optimizer_runs():
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(opt, op=hvd.Adasum)
    x = torch.randn(16, 4)
    y = x.sum(1, keepdim=True)
    l0 = None
    for _ in range(10):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        l0 = l0 or float(loss)
    assert float(loss) < l0


# -------------------------------------------------------------------- sync BN
def test_sync_batch_norm_matches_local_bn():
    # Replicated data: sync-BN global stats == local batch stats.
    torch.manual_seed(0)
    x = torch.randn(6, 3, 4, 4)
    sbn = hvd.SyncBatchNorm(3)
    bn = torch.nn.BatchNorm2d(3)
    sbn.train()
    bn.train()
    out_s = sbn(x)
    out_l = bn(x)
    assert torch.allclose(out_s, out_l, atol=1e-4)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-4)
    assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-3)


def test_sync_batch_norm_grad_flows():
    x = torch.randn(4, 2, requires_grad=True)
    sbn = hvd.SyncBatchNorm(2)
    sbn.train()
    sbn(x).sum().backward()
    assert x.grad is not None


def test_sync_batch_norm_eval_uses_running_stats():
    x = torch.randn(4, 2)
    sbn = hvd.SyncBatchNorm(2)
    sbn.eval()
    out = sbn(x)
    assert torch.allclose(out, (x - sbn.running_mean) /
                          torch.sqrt(sbn.running_var + sbn.eps), atol=1e-4)


# -------------------------------------------------------------------- elastic
def test_torch_state_commit_restore():
    model = torch.nn.Linear(2, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    state = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=3)
    state.commit()
    with torch.no_grad():
        model.weight.add_(1.0)
    state.epoch = 7
    state.restore()
    assert state.epoch == 3
    # weights rolled back
    state2 = hvd.elastic.TorchState(model=model, optimizer=opt, epoch=0)
    state2.sync()


def test_elastic_sampler():
    data = list(range(20))
    s = hvd.elastic.ElasticSampler(data, shuffle=False)
    idx = list(iter(s))
    assert len(idx) == len(s)
    s.record_batch(0, 2)
    n_before = len(s.processed_indices)
    assert n_before > 0
    s.reset()
    remaining = set(s.remaining_indices)
    assert not (remaining & s.processed_indices)
    sd = s.state_dict()
    s2 = hvd.elastic.ElasticSampler(data, shuffle=False)
    s2.load_state_dict(sd)
    assert s2.processed_indices == s.processed_indices


# ----------------------------------------------------------------------- join
def test_join_single_process():
    assert hvd.join() == hvd.size() - 1


# ------------------------------------------------------------------ bf16 wire
def test_bf16_bridge_roundtrip():
    t = torch.randn(8).to(torch.bfloat16)
    out = hvd.allreduce(t, op=hvd.Average)
    assert out.dtype == torch.bfloat16
    assert torch.allclose(out.float(), t.float(), atol=1e-2)


# ------------------------------------------------- auto-bucketing / streams
def test_auto_bucketing_collapses_dispatches(monkeypatch):
    """A 100-parameter model must cost a handful of fused collectives per
    step, not one per parameter (round-1 VERDICT weak #6: >=5x fewer
    transfers; auto-buckets by HOROVOD_FUSION_THRESHOLD)."""
    from horovod_tpu.torch import mpi_ops as M
    model = torch.nn.Sequential(
        *[torch.nn.Linear(4, 4) for _ in range(50)])  # 100 parameters
    calls = []
    orig_g, orig_a = M._C.grouped_allreduce, M._C.allreduce
    monkeypatch.setattr(M._C, "grouped_allreduce",
                        lambda *a, **k: (calls.append("grouped"),
                                         orig_g(*a, **k))[1])
    monkeypatch.setattr(M._C, "allreduce",
                        lambda *a, **k: (calls.append("single"),
                                         orig_a(*a, **k))[1])
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters())
    x = torch.randn(8, 4)
    loss = model(x).sum()
    loss.backward()
    opt.step()
    assert 1 <= len(calls) <= 100 // 5, calls  # >=5x fewer dispatches
    assert all(c == "grouped" for c in calls), calls


def test_bucket_bytes_zero_restores_per_parameter(monkeypatch):
    from horovod_tpu.torch import mpi_ops as M
    model = torch.nn.Sequential(torch.nn.Linear(4, 4), torch.nn.Linear(4, 4))
    calls = []
    orig_a = M._C.allreduce
    monkeypatch.setattr(M._C, "allreduce",
                        lambda *a, **k: (calls.append(1), orig_a(*a, **k))[1])
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters(), bucket_bytes=0)
    loss = model(torch.randn(2, 4)).sum()
    loss.backward()
    opt.step()
    assert len(calls) == 4  # one per parameter


def test_async_dispatch_overlaps_on_stream_pool():
    """allreduce_async must return before the collective completes when a
    stream pool is active (round-1 VERDICT: async ops dispatched the whole
    chain synchronously)."""
    import threading
    import time as _time
    from horovod_tpu.torch import mpi_ops as M
    release = threading.Event()
    started = threading.Event()
    orig = M._run_allreduce

    def slow(*a, **k):
        started.set()
        release.wait(timeout=10)
        return orig(*a, **k)

    M._run_allreduce = slow
    try:
        t0 = _time.monotonic()
        h = hvd.allreduce_async(torch.ones(4), name="overlap_probe")
        dispatch_time = _time.monotonic() - t0
        assert dispatch_time < 5.0  # returned while collective blocked
        assert started.wait(timeout=10)
        assert not hvd.poll(h)
        release.set()
        out = hvd.synchronize(h)
        assert torch.allclose(out, torch.ones(4))
    finally:
        M._run_allreduce = orig
        release.set()


def test_sparse_allreduce_async():
    """Sparse COO allreduce via ragged gather + coalesce (reference:
    torch/mpi_ops.py:512-531): per-chip contributions sum; Average
    divides by chip count, so single-process values round-trip."""
    import torch
    import horovod_tpu.torch as hvd

    t = torch.sparse_coo_tensor(
        torch.tensor([[0, 3], [1, 0]]), torch.tensor([2.0, 4.0]),
        (5, 2))
    handle = hvd.sparse_allreduce_async(t, name="sp1", op=hvd.Average)
    out = handle()
    assert out.is_sparse
    dense = out.to_dense()
    # 8 chips each contribute the process value; coalesce sums 8 copies,
    # Average divides by 8 -> original values.
    np.testing.assert_allclose(dense.numpy(), t.to_dense().numpy(),
                               rtol=1e-6)
    # Sum: 8x
    out2 = hvd.sparse_allreduce_async(t, name="sp2", op=hvd.Sum)()
    np.testing.assert_allclose(out2.to_dense().numpy(),
                               t.to_dense().numpy() * hvd.size(),
                               rtol=1e-6)
