"""Golden test of the CI pipeline generator (reference strategy:
test/single/test_buildkite.py compares gen-pipeline.sh output to
test/single/data/expected_buildkite_pipeline.yaml).

Three properties:
  * the committed .ci/pipeline.yaml matches a fresh generation — editing
    the matrix without regenerating fails CI itself;
  * every HOROVOD_* env var any step sets is a registered knob — the
    pipeline can't drift from the config system (docs/knobs.md);
  * every unit-tier test file in the tree is covered by some step — a new
    test file that no CI step runs is a silent coverage hole.
"""

import glob
import importlib.util
import os

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_gen():
    spec = importlib.util.spec_from_file_location(
        "gen_ci", os.path.join(REPO, "scripts", "gen_ci.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_committed_pipeline_is_current():
    gen = _load_gen()
    steps = gen.build_steps()
    gen.validate(steps)
    with open(os.path.join(REPO, ".ci", "pipeline.yaml")) as f:
        committed = f.read()
    assert committed == gen.render(steps), \
        "stale .ci/pipeline.yaml — run: python scripts/gen_ci.py"


def test_pipeline_parses_and_env_vars_are_registered_knobs():
    from horovod_tpu.common import knobs
    with open(os.path.join(REPO, ".ci", "pipeline.yaml")) as f:
        doc = yaml.safe_load(f)
    assert isinstance(doc["steps"], list) and len(doc["steps"]) >= 10
    for step in doc["steps"]:
        assert step["label"] and step["command"]
        assert step["timeout_in_minutes"] > 0
        for k in step.get("env", {}):
            if k.startswith("HOROVOD_"):
                assert k in knobs.KNOBS, \
                    f"step '{step['label']}' sets unregistered knob {k}"


def test_every_unit_test_file_is_scheduled():
    gen = _load_gen()
    scheduled = {t for s in gen.build_steps()
                 for t in s["command"].split()
                 if t.startswith("tests/") and t.endswith(".py")}
    on_disk = {os.path.relpath(p, REPO)
               for p in glob.glob(os.path.join(REPO, "tests", "test_*.py"))}
    missing = on_disk - scheduled
    assert not missing, f"test files no CI step runs: {sorted(missing)}"
