"""Data loader utilities (reference: horovod/data/data_loader_base.py
behavior: async queue-backed iteration, exception propagation, sharding)."""

import threading
import time

import numpy as np
import pytest

from horovod_tpu.data import (AsyncDataLoaderMixin, AsyncNumpyDataLoader,
                              BaseDataLoader, NumpyDataLoader,
                              ParquetDataLoader, shard_indices)


def test_shard_indices_cover_and_balance():
    shards = [shard_indices(10, r, 4) for r in range(4)]
    assert all(len(s) == 3 for s in shards)  # ceil(10/4) with wrap pad
    covered = set(np.concatenate(shards).tolist())
    assert covered == set(range(10))


def test_shard_indices_shuffle_deterministic():
    a = shard_indices(100, 1, 4, shuffle=True, seed=7)
    b = shard_indices(100, 1, 4, shuffle=True, seed=7)
    c = shard_indices(100, 1, 4, shuffle=True, seed=8)
    assert np.array_equal(a, b)
    assert not np.array_equal(a, c)


def test_numpy_loader_batches_and_len():
    x = np.arange(20).reshape(10, 2)
    y = np.arange(10)
    dl = NumpyDataLoader([x, y], batch_size=4)
    batches = list(dl)
    assert len(batches) == len(dl) == 3
    bx, by = batches[0]
    assert bx.shape == (4, 2) and by.shape == (4,)
    assert np.concatenate([b[1] for b in batches]).tolist() == list(range(10))


def test_numpy_loader_drop_last_and_sharding():
    x = np.arange(10)
    dl = NumpyDataLoader([x], batch_size=4, rank=0, num_workers=2,
                         drop_last=True)
    batches = list(dl)
    assert len(batches) == 1 and batches[0][0].shape == (4,)


def test_numpy_loader_epoch_reshuffle():
    dl = NumpyDataLoader([np.arange(32)], batch_size=32, shuffle=True)
    dl.set_epoch(0)
    e0 = list(dl)[0][0]
    dl.set_epoch(1)
    e1 = list(dl)[0][0]
    assert not np.array_equal(e0, e1)
    assert sorted(e0.tolist()) == sorted(e1.tolist())


def test_async_loader_matches_sync_and_overlaps():
    x = np.arange(64).reshape(32, 2)
    sync = NumpyDataLoader([x], batch_size=8)
    async_ = AsyncNumpyDataLoader([x], batch_size=8,
                                  async_loader_queue_size=4)
    for (a,), (b,) in zip(sync, async_):
        np.testing.assert_array_equal(a, b)
    async_.close()
    # queue_size=0 degrades to sync
    plain = AsyncNumpyDataLoader([x], batch_size=8,
                                 async_loader_queue_size=0)
    assert len(list(plain)) == 4


def test_async_loader_propagates_exceptions():
    class Boom(BaseDataLoader):
        def __len__(self):
            return 1

        def _iterate(self):
            yield 1
            raise RuntimeError("producer failed")

    class AsyncBoom(AsyncDataLoaderMixin, Boom):
        pass

    it = iter(AsyncBoom(async_loader_queue_size=2))
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer failed"):
        next(it)


def test_async_loader_producer_runs_ahead():
    produced = []

    class Slow(BaseDataLoader):
        def __len__(self):
            return 4

        def _iterate(self):
            for i in range(4):
                produced.append(i)
                yield i

    class AsyncSlow(AsyncDataLoaderMixin, Slow):
        pass

    it = iter(AsyncSlow(async_loader_queue_size=8))
    first = next(it)
    time.sleep(0.2)  # producer thread should have drained the source
    assert first == 0
    assert len(produced) == 4  # ran ahead of the consumer
    assert list(it) == [1, 2, 3]


def test_parquet_loader_roundtrip(tmp_path):
    from horovod_tpu.spark.store import FilesystemStore
    store = FilesystemStore(str(tmp_path))
    x = np.random.RandomState(0).randn(20, 3).astype(np.float32)
    y = np.arange(20, dtype=np.int64)
    path = store.write_parquet(str(tmp_path / "ds"), {"x": x, "y": y})

    dl = ParquetDataLoader(path, batch_size=6)
    rows = list(dl)
    assert len(rows) == len(dl) == 4
    got_y = np.concatenate([b["y"] for b in rows])
    assert sorted(got_y.tolist()) == list(range(20))

    # two workers read disjoint shards covering everything
    r0 = np.concatenate([b["y"] for b in
                         ParquetDataLoader(path, 6, rank=0, num_workers=2)])
    r1 = np.concatenate([b["y"] for b in
                         ParquetDataLoader(path, 6, rank=1, num_workers=2)])
    assert set(r0.tolist()) | set(r1.tolist()) == set(range(20))


def test_parquet_loader_more_workers_than_rows(tmp_path):
    """Every worker must get a non-empty, equal-batch-count shard even when
    rows < workers (regression: empty shards deadlock collectives)."""
    from horovod_tpu.spark.store import FilesystemStore
    store = FilesystemStore(str(tmp_path))
    y = np.arange(4, dtype=np.int64)
    path = store.write_parquet(str(tmp_path / "tiny"), {"y": y})
    lens = []
    for r in range(6):
        dl = ParquetDataLoader(path, batch_size=2, rank=r, num_workers=6)
        batches = list(dl)
        assert len(batches) >= 1, r
        lens.append(len(batches))
    assert len(set(lens)) == 1  # same batch count everywhere


def test_async_loader_early_break_stops_producer(tmp_path):
    """Breaking out of iteration must stop the producer thread
    (regression: orphan thread spinning in _safe_put forever)."""
    import threading
    before = threading.active_count()
    x = np.arange(1000)
    dl = AsyncNumpyDataLoader([x], batch_size=1, async_loader_queue_size=2)
    for batch in dl:
        break
    time.sleep(0.3)
    assert threading.active_count() <= before + 1  # producer gone/joining


# ------------------------------------------------------------ image folder
def _make_image_tree(root, classes=("cat", "dog", "owl"), per_class=7,
                     size=12):
    from PIL import Image
    rng = np.random.RandomState(0)
    for c in classes:
        d = root / c
        d.mkdir(parents=True)
        for i in range(per_class):
            Image.fromarray(
                rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
            ).save(d / f"img{i}.png")


def test_image_folder_loader_shapes_and_labels(tmp_path):
    from horovod_tpu.data import ImageFolderDataLoader
    _make_image_tree(tmp_path)
    dl = ImageFolderDataLoader(str(tmp_path), batch_size=4, image_size=8)
    assert dl.classes == ["cat", "dog", "owl"]
    batches = list(dl)
    assert sum(len(y) for _, y in batches) == 21
    for x, y in batches:
        assert x.dtype == np.uint8 and x.shape[1:] == (8, 8, 3)
        assert y.dtype == np.int32
    # every class seen with its sorted-directory id
    all_y = np.concatenate([y for _, y in batches])
    assert set(all_y.tolist()) == {0, 1, 2}


def test_image_folder_loader_sharding_partitions(tmp_path):
    from horovod_tpu.data import ImageFolderDataLoader
    _make_image_tree(tmp_path, per_class=8)  # 24 images
    seen = []
    for r in range(2):
        dl = ImageFolderDataLoader(str(tmp_path), batch_size=6,
                                   image_size=8, rank=r, num_workers=2)
        assert len(dl) == 2
        seen.append(np.concatenate([y for _, y in dl]))
    # equal per-worker counts (wrap-pad convention), full coverage
    assert len(seen[0]) == len(seen[1]) == 12


def test_async_image_folder_matches_sync(tmp_path):
    from horovod_tpu.data import (AsyncImageFolderDataLoader,
                                  ImageFolderDataLoader)
    _make_image_tree(tmp_path)
    sync = ImageFolderDataLoader(str(tmp_path), batch_size=5, image_size=8)
    asy = AsyncImageFolderDataLoader(str(tmp_path), batch_size=5,
                                     image_size=8,
                                     async_loader_queue_size=4)
    for (x1, y1), (x2, y2) in zip(sync, asy):
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)
    asy.close()


def test_image_folder_loader_rejects_empty(tmp_path):
    from horovod_tpu.data import ImageFolderDataLoader
    with pytest.raises(ValueError, match="class directories"):
        ImageFolderDataLoader(str(tmp_path), batch_size=2)


# ------------------------------------------------------------ shuffle buffer
def test_shuffle_buffer_covers_all_rows_reordered(tmp_path):
    from horovod_tpu.data.loader import (ShuffleBufferLoader,
                                         StreamingParquetDataLoader)
    from horovod_tpu.spark import FilesystemStore
    store = FilesystemStore(str(tmp_path))
    store.write_parquet(str(tmp_path / "ds"),
                        {"x": np.arange(100, dtype=np.float64)})
    base = StreamingParquetDataLoader(str(tmp_path / "ds"), batch_size=8)
    dl = ShuffleBufferLoader(base, buffer_rows=32, seed=1)
    rows = np.concatenate([b["x"] for b in dl])
    assert sorted(rows.tolist()) == list(range(100))  # full coverage
    assert rows.tolist() != list(range(100))          # actually shuffled
    dl.set_epoch(1)
    rows2 = np.concatenate([b["x"] for b in dl])
    assert rows2.tolist() != rows.tolist()            # reshuffles per epoch
    assert sorted(rows2.tolist()) == list(range(100))


def test_shuffle_buffer_rejects_bad_size(tmp_path):
    from horovod_tpu.data.loader import ShuffleBufferLoader
    with pytest.raises(ValueError, match="buffer_rows"):
        ShuffleBufferLoader(None, buffer_rows=0)


def test_shuffle_buffer_len_matches_yielded_batches(tmp_path):
    # The wrapper absorbs whole batches during fill and re-chunks the
    # buffer at drain, so its batch count differs from the inner
    # loader's; __len__ must track the actual yield count for uniform
    # inner batches (steps-per-epoch accounting depends on it).
    from horovod_tpu.data.loader import (ShuffleBufferLoader,
                                         StreamingParquetDataLoader)
    from horovod_tpu.spark import FilesystemStore
    store = FilesystemStore(str(tmp_path))
    store.write_parquet(str(tmp_path / "ds"),
                        {"x": np.arange(96, dtype=np.float64)})
    # 200 > dataset: the whole dataset is absorbed and re-chunked
    for buffer_rows in (5, 8, 32, 33, 96, 200):
        base = StreamingParquetDataLoader(str(tmp_path / "ds"),
                                          batch_size=8)
        dl = ShuffleBufferLoader(base, buffer_rows=buffer_rows, seed=3)
        assert len(dl) == sum(1 for _ in dl), buffer_rows
    # ragged tail (100 rows, bs=8): exact via the inner num_rows,
    # including buffers at/above the dataset size and mid-ragged-batch
    store.write_parquet(str(tmp_path / "ds100"),
                        {"x": np.arange(100, dtype=np.float64)})
    for buffer_rows in (5, 96, 97, 98, 100, 104, 200):
        base = StreamingParquetDataLoader(str(tmp_path / "ds100"),
                                          batch_size=8)
        dl = ShuffleBufferLoader(base, buffer_rows=buffer_rows, seed=3)
        assert len(dl) == sum(1 for _ in dl), buffer_rows


def test_list_parquet_files_orders_numerically_across_widths(tmp_path):
    # Datasets may mix part-number widths (writer versions differ);
    # read order must follow the numeric part index, not string order.
    from horovod_tpu.data.loader import list_parquet_files
    d = tmp_path / "ds"
    d.mkdir()
    for name in ("part-000000011.parquet", "part-0000000000002.parquet",
                 "part-000000001.parquet", "extra.parquet"):
        (d / name).write_bytes(b"")
    got = [p.rsplit("/", 1)[-1] for p in list_parquet_files(str(d))]
    assert got == ["part-000000001.parquet", "part-0000000000002.parquet",
                   "part-000000011.parquet", "extra.parquet"]
