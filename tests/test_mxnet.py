"""MXNet frontend: import gating without mxnet, and the REAL binding
paths (ops, DistributedOptimizer.update, DistributedTrainer.
_allreduce_grads, deferred-init broadcast hook) executed against the
strict contract shim in tests/mxnet_shim.py (reference:
test/parallel/test_mxnet.py; VERDICT-r2 #8 — these paths had never run
because mxnet is not installable here)."""

import sys

import numpy as np
import pytest

import horovod_tpu.mxnet as hmx
import mxnet_shim


def test_topology_without_mxnet(hvd):
    # topology APIs never need mxnet
    assert hmx.size() == 8
    assert hmx.local_size() == 8


def _have_real_mxnet():
    try:
        import mxnet  # noqa: F401
        return True
    except ImportError:
        return False


@pytest.mark.skipif(_have_real_mxnet(),
                    reason="mxnet installed; gate not hit")
def test_ops_raise_actionable_importerror(hvd):
    with pytest.raises(ImportError, match="mxnet"):
        hmx.allreduce(np.ones(3))
    with pytest.raises(ImportError, match="mxnet"):
        hmx.DistributedOptimizer(object())


@pytest.fixture()
def mx(hvd, monkeypatch):
    shim = mxnet_shim.build_module()
    monkeypatch.setitem(sys.modules, "mxnet", shim)
    return shim


# ------------------------------------------------------------------- ops
def test_allreduce_sum_average(mx):
    t = mx.nd.array([1.0, 2.0])
    out = hmx.allreduce(t, op=hmx.Sum)
    np.testing.assert_allclose(out.asnumpy(), [8.0, 16.0])
    out = hmx.allreduce(t, average=True)
    np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])


def test_inplace_and_grouped(mx):
    t = mx.nd.array([2.0])
    hmx.allreduce_(t, average=True)
    np.testing.assert_allclose(t.asnumpy(), [2.0])
    ts = [mx.nd.array([float(i)]) for i in range(3)]
    hmx.grouped_allreduce_(ts, average=False)
    for i, t in enumerate(ts):
        np.testing.assert_allclose(t.asnumpy(), [8.0 * i])


def test_broadcast_allgather_alltoall(mx):
    t = mx.nd.array([[5.0]])
    np.testing.assert_allclose(
        hmx.broadcast(t, root_rank=2).asnumpy(), [[5.0]])
    g = hmx.allgather(mx.nd.array([[1.0, 2.0]]))
    assert g.shape == (8, 2)
    a = hmx.alltoall(mx.nd.array(np.arange(8.0)))
    assert a.shape == (8,)


# -------------------------------------------------- DistributedOptimizer
def test_distributed_optimizer_update_executes(mx):
    """update(): grads allreduced (sum over 8 chips), rescale_grad
    normalized by size -> the step equals a LOCAL sgd step."""
    opt = hmx.DistributedOptimizer(mx.optimizer.SGD(learning_rate=0.5))
    w = mx.nd.array([1.0, 2.0])
    g = mx.nd.array([0.2, -0.4])
    opt.update(0, w, g, None)
    np.testing.assert_allclose(w.asnumpy(), [1.0 - 0.5 * 0.2,
                                             2.0 + 0.5 * 0.4], rtol=1e-6)


def test_distributed_optimizer_update_list_and_groups(mx):
    """The index-list form and the num_groups fused form both execute."""
    opt = hmx.DistributedOptimizer(mx.optimizer.SGD(learning_rate=1.0),
                                   num_groups=2)
    ws = [mx.nd.array([float(i)]) for i in range(4)]
    gs = [mx.nd.array([0.1 * (i + 1)]) for i in range(4)]
    for i, (w, g) in enumerate(zip(ws, gs)):
        opt.update([i], [w], [g], [None])
    for i, w in enumerate(ws):
        np.testing.assert_allclose(w.asnumpy(), [i - 0.1 * (i + 1)],
                                   rtol=1e-6)


def test_distributed_optimizer_forwards_hyperparams(mx):
    inner = mx.optimizer.SGD(learning_rate=0.1)
    opt = hmx.DistributedOptimizer(inner)
    opt.set_learning_rate(0.7)
    assert inner.lr == 0.7
    assert opt.create_state_multi_precision(0, None) is None


# ---------------------------------------------------- DistributedTrainer
def test_distributed_trainer_allreduce_grads_and_step(mx):
    params = {}
    for i in range(3):
        p = mx.gluon.Parameter(f"w{i}")
        p.initialize([float(i), float(i)])
        p._grad = mx.nd.array([0.5, -0.5])
        params[f"w{i}"] = p
    trainer = hmx.DistributedTrainer(params, "sgd",
                                     {"learning_rate": 0.2})
    trainer.step(1)
    for i in range(3):
        np.testing.assert_allclose(
            params[f"w{i}"].data().asnumpy(),
            [i - 0.2 * 0.5, i + 0.2 * 0.5], rtol=1e-6)


def test_distributed_trainer_grouped_and_null_grads(mx):
    params = {}
    for i in range(4):
        p = mx.gluon.Parameter(f"w{i}",
                               grad_req="null" if i == 3 else "write")
        p.initialize([1.0])
        p._grad = mx.nd.array([1.0])
        params[f"w{i}"] = p
    trainer = hmx.DistributedTrainer(params, "sgd",
                                     {"learning_rate": 1.0}, num_groups=2)
    trainer.step(1)
    for i in range(3):
        np.testing.assert_allclose(params[f"w{i}"].data().asnumpy(), [0.0])
    # grad_req='null' params are excluded from reduce AND update
    np.testing.assert_allclose(params["w3"].data().asnumpy(), [1.0])


def test_distributed_trainer_unwraps_distributed_optimizer(mx):
    """Unwrapping must also undo the wrapper's in-place rescale_grad
    division, or the step would be divided by size() twice."""
    inner = mx.optimizer.SGD(learning_rate=1.0)
    wrapped = hmx.DistributedOptimizer(inner)
    p = mx.gluon.Parameter("w")
    p.initialize([2.0])
    p._grad = mx.nd.array([0.5])
    with pytest.warns(UserWarning, match="unwrapped"):
        trainer = hmx.DistributedTrainer({"w": p}, wrapped)
    assert trainer._optimizer is inner
    trainer.step(1)
    # one local-equivalent sgd step: 2.0 - 1.0 * 0.5
    np.testing.assert_allclose(p.data().asnumpy(), [1.5], rtol=1e-6)


# ------------------------------------------------- broadcast_parameters
def test_broadcast_parameters_immediate_and_deferred(mx):
    ready = mx.gluon.Parameter("a")
    ready.initialize([3.0, 4.0])
    deferred = mx.gluon.Parameter("b")  # no data yet
    hmx.broadcast_parameters({"a": ready, "b": deferred}, root_rank=0)
    np.testing.assert_allclose(ready.data().asnumpy(), [3.0, 4.0])
    # the deferred param's _init_impl was wrapped: first initialization
    # must run the broadcast hook and leave the param usable
    deferred.initialize([7.0])
    np.testing.assert_allclose(deferred.data().asnumpy(), [7.0])


def test_broadcast_parameters_rejects_non_dict(mx):
    with pytest.raises(ValueError, match="invalid params"):
        hmx.broadcast_parameters([1, 2, 3])
