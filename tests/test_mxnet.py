"""MXNet frontend: full op coverage when mxnet is installed, gating
behavior when it is not (reference: test/parallel/test_mxnet.py)."""

import numpy as np
import pytest

import horovod_tpu.mxnet as hmx


def test_topology_without_mxnet(hvd):
    # topology APIs never need mxnet
    assert hmx.size() == 8
    assert hmx.local_size() == 8


def _have_mxnet():
    try:
        import mxnet  # noqa: F401
        return True
    except ImportError:
        return False


@pytest.mark.skipif(_have_mxnet(), reason="mxnet installed; gate not hit")
def test_ops_raise_actionable_importerror(hvd):
    with pytest.raises(ImportError, match="mxnet"):
        hmx.allreduce(np.ones(3))
    with pytest.raises(ImportError, match="mxnet"):
        hmx.DistributedOptimizer(object())


@pytest.mark.skipif(not _have_mxnet(), reason="mxnet not installed")
class TestWithMXNet:
    def test_allreduce_sum_average(self, hvd):
        import mxnet as mx
        t = mx.nd.array([1.0, 2.0])
        out = hmx.allreduce(t, op=hmx.Sum)
        np.testing.assert_allclose(out.asnumpy(), [8.0, 16.0])
        out = hmx.allreduce(t, average=True)
        np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])

    def test_inplace_and_grouped(self, hvd):
        import mxnet as mx
        t = mx.nd.array([2.0])
        hmx.allreduce_(t, average=True)
        np.testing.assert_allclose(t.asnumpy(), [2.0])
        ts = [mx.nd.array([float(i)]) for i in range(3)]
        hmx.grouped_allreduce_(ts, average=False)
        for i, t in enumerate(ts):
            np.testing.assert_allclose(t.asnumpy(), [8.0 * i])

    def test_broadcast_and_allgather(self, hvd):
        import mxnet as mx
        t = mx.nd.array([[5.0]])
        np.testing.assert_allclose(
            hmx.broadcast(t, root_rank=2).asnumpy(), [[5.0]])
        g = hmx.allgather(mx.nd.array([[1.0, 2.0]]))
        assert g.shape == (8, 2)

    def test_distributed_trainer_step(self, hvd):
        import mxnet as mx
        net = mx.gluon.nn.Dense(1)
        net.initialize()
        x = mx.nd.random.normal(shape=(4, 3))
        with mx.autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer = hmx.DistributedTrainer(
            net.collect_params(), "sgd", {"learning_rate": 0.1})
        trainer.step(4)
