"""DistributedOptimizer / gradient-sync tests (reference analog:
test/parallel/test_torch.py optimizer coverage, gradient_aggregation tests)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu.optimizer import sync_gradients, distributed_optimizer
from horovod_tpu.ops.compression import Compression

from horovod_tpu.ops._compat import shard_map


def _data_mesh():
    """The legacy single-axis data mesh these tests' shard_maps hardcode
    ("hvd") — built directly from the devices, independent of the
    runtime's resolved training mesh, so the CI layout knob dimension
    (HOROVOD_LAYOUT=auto; docs/parallelism.md) keeps this suite green."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh as _Mesh
    return _Mesh(_np.array(jax.devices()), ("hvd",))


def _shmap(fn, mesh, n_in, n_out=1):
    return shard_map(fn, mesh=mesh, in_specs=(P("hvd"),) * n_in,
                     out_specs=(P("hvd"),) * n_out if n_out > 1 else P("hvd"))


def test_sync_gradients_mean(hvd):
    mesh = _data_mesh()
    n = hvd.size()
    grads = {"w": np.random.RandomState(0).randn(n, 4).astype(np.float32),
             "b": np.random.RandomState(1).randn(n, 2).astype(np.float32)}

    def body(w, b):
        g = sync_gradients({"w": w, "b": b}, "hvd")
        return g["w"], g["b"]

    f = jax.jit(_shmap(body, mesh, 2, 2))
    w, b = f(grads["w"], grads["b"])
    np.testing.assert_allclose(np.asarray(w)[0], grads["w"].mean(axis=0),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(b)[3], grads["b"].mean(axis=0),
                               rtol=1e-5)


def test_sync_gradients_fusion_matches_unfused(hvd):
    """Bucketed (fused) sync must be numerically identical to per-tensor."""
    mesh = _data_mesh()
    n = hvd.size()
    rng = np.random.RandomState(42)
    gs = [rng.randn(n, k + 1).astype(np.float32) for k in range(6)]

    def body_fused(*leaves):
        return tuple(sync_gradients(list(leaves), "hvd",
                                    fusion_threshold_bytes=64))

    def body_unfused(*leaves):
        return tuple(sync_gradients(list(leaves), "hvd",
                                    fusion_threshold_bytes=1))

    f1 = jax.jit(_shmap(body_fused, mesh, 6, 6))
    f2 = jax.jit(_shmap(body_unfused, mesh, 6, 6))
    for a, b in zip(f1(*gs), f2(*gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_sync_gradients_compression_fp16(hvd):
    mesh = _data_mesh()
    n = hvd.size()
    g = np.random.RandomState(3).randn(n, 32).astype(np.float32)

    def body(x):
        return sync_gradients(x, "hvd", compression=Compression.fp16)

    out = jax.jit(_shmap(body, mesh, 1))(g)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out)[0], g.mean(axis=0), atol=2e-3)


def test_distributed_optimizer_end_to_end(hvd):
    """Data-parallel SGD: one step with per-chip different grads must equal
    single-worker SGD on the mean gradient."""
    mesh = _data_mesh()
    n = hvd.size()
    w0 = np.ones(4, np.float32)
    lr = 0.1
    opt = distributed_optimizer(optax.sgd(lr), axis_name="hvd")
    batches = np.random.RandomState(7).randn(n, 4).astype(np.float32)

    def loss(w, x):
        return jnp.sum((w - x) ** 2)

    def step(w, x):
        # w arrives replicated per chip ([1? no...]) — pass with P() spec
        g = jax.grad(loss)(w, x[0])
        state = opt.init(w)
        updates, _ = opt.update(g, state, w)
        return optax.apply_updates(w, updates)

    f = jax.jit(shard_map(step, mesh=mesh,
                          in_specs=(P(), P("hvd")), out_specs=P(),
                          check_vma=False))
    w1 = np.asarray(f(jnp.asarray(w0), jnp.asarray(batches)))
    mean_grad = np.mean([2 * (w0 - b) for b in batches], axis=0)
    np.testing.assert_allclose(w1, w0 - lr * mean_grad, rtol=1e-5)


def test_backward_passes_per_step(hvd):
    """Local aggregation (reference: gradient_aggregation.py): updates apply
    only every Nth micro-batch, using the averaged accumulated gradient."""
    mesh = _data_mesh()
    n = hvd.size()
    lr = 1.0
    opt = distributed_optimizer(optax.sgd(lr), axis_name="hvd",
                                backward_passes_per_step=2)
    w0 = jnp.zeros(3)
    g1 = np.random.RandomState(0).randn(n, 3).astype(np.float32)
    g2 = np.random.RandomState(1).randn(n, 3).astype(np.float32)

    def two_steps(w, a, b):
        state = opt.init(w)
        u1, state = opt.update(a[0], state, w)
        w = optax.apply_updates(w, u1)
        u2, state = opt.update(b[0], state, w)
        w = optax.apply_updates(w, u2)
        return w

    f = jax.jit(shard_map(two_steps, mesh=mesh,
                          in_specs=(P(), P("hvd"), P("hvd")),
                          out_specs=P(), check_vma=False))
    w = np.asarray(f(w0, jnp.asarray(g1), jnp.asarray(g2)))
    expected = -lr * (g1.mean(axis=0) + g2.mean(axis=0)) / 2.0
    np.testing.assert_allclose(w, expected, rtol=1e-5)


def test_distributed_grad(hvd):
    """DistributedGradientTape analog."""
    mesh = _data_mesh()
    n = hvd.size()
    xs = np.random.RandomState(5).randn(n, 4).astype(np.float32)

    def loss(w, x):
        return jnp.sum(w * x)

    def body(w, x):
        g = hvd_mod.distributed_grad(loss, axis_name="hvd")(w, x[0])
        return g

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P("hvd")),
                          out_specs=P(), check_vma=False))
    g = np.asarray(f(jnp.ones(4), jnp.asarray(xs)))
    np.testing.assert_allclose(g, xs.mean(axis=0), rtol=1e-5)
