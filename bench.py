"""Benchmark: flagship training throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

Protocol mirrors the reference's synthetic benchmarks (reference:
examples/pytorch/pytorch_synthetic_benchmark.py:104-109 — timed iterations
of a full train step on synthetic data, mean over batches after warmup).

``vs_baseline`` is model-FLOPs utilization (MFU) relative to the chip's
bf16 peak — the hardware-normalized analog of the reference's
scaling-efficiency-vs-ideal metric (BASELINE.md: >=90% scaling efficiency
target).  MFU is computed from 6*N*tokens train FLOPs.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets).
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "cpu": 0.5,  # nominal, so CPU smoke runs produce a finite ratio
}


def detect_chip() -> str:
    import os
    import jax
    kind = jax.devices()[0].device_kind.lower()
    plat = jax.devices()[0].platform.lower()
    if "cpu" in kind or plat == "cpu":
        return "cpu"
    for key in ("v6e", "v5p", "v5e", "v4"):
        if key in kind:
            return key
    return os.environ.get("PALLAS_AXON_TPU_GEN", "") or "v5e"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--model", default="bench",
                    choices=["bench", "tiny", "mini", "1b", "8b"])
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (smoke mode)")
    args = ap.parse_args()

    if args.cpu:
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import llama
    from horovod_tpu.parallel.data_parallel import (make_train_step,
                                                    replicate, shard_batch)

    # ~350M-param decoder: big enough to keep the MXU busy on one chip,
    # small enough to compile fast and fit HBM with optimizer state.
    cfgs = dict(llama.CONFIGS)
    cfgs["bench"] = llama.LlamaConfig(
        vocab=32768, dim=1024, n_layers=8, n_heads=16, n_kv_heads=8,
        ffn_dim=4096, max_seq=max(2048, args.seq),
        dtype=jnp.bfloat16)
    cfg = cfgs[args.model]
    if args.cpu:
        cfg = llama.CONFIGS["tiny"]
        args.batch, args.seq = 4, 64

    hvd.init()
    mesh = hvd.mesh()
    n_chips = hvd.size()

    params = llama.init(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    opt = optax.adamw(3e-4, weight_decay=0.01)
    step = make_train_step(lambda p, ids: llama.loss_fn(p, ids, cfg),
                           opt, mesh)
    params = replicate(params, mesh)
    opt_state = replicate(opt.init(params), mesh)

    global_batch = args.batch * n_chips
    rng = np.random.RandomState(0)
    ids_host = rng.randint(0, cfg.vocab, (global_batch, args.seq + 1),
                           dtype=np.int32)
    ids = shard_batch(jnp.asarray(ids_host), mesh)

    for _ in range(args.warmup):
        params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens = args.steps * global_batch * args.seq
    tok_per_sec = tokens / dt
    tok_per_sec_chip = tok_per_sec / n_chips

    chip = detect_chip()
    peak = PEAK_TFLOPS.get(chip, PEAK_TFLOPS["v5e"]) * 1e12
    train_flops_per_token = 6.0 * n_params
    mfu = (tok_per_sec_chip * train_flops_per_token) / peak

    print(json.dumps({
        "metric": f"llama-{n_params/1e6:.0f}M train tokens/sec/chip "
                  f"({chip}, bf16, seq={args.seq})",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
