"""Benchmark: flagship training throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N,
   "metrics": {...}}

The "metrics" field embeds a condensed hvd.metrics_snapshot() (plan-cache
hit rate, controller cycles/cache rate, collective op/byte counts, stall
warnings — docs/metrics.md) so BENCH rows carry controller-level evidence
alongside MFU.

Protocol mirrors the reference's synthetic benchmarks (reference:
examples/pytorch/pytorch_synthetic_benchmark.py:104-109 — timed iterations
of a full train step on synthetic data), made honest for a remote-dispatch
TPU platform:

  * All timed steps run inside ONE compiled ``lax.scan`` program
    (make_scanned_train_step), so per-dispatch tunnel latency is amortized
    and cannot dominate or vanish from the measurement.
  * The timer stops only after the per-step losses are fetched to the HOST
    (device-to-host transfer) — ``block_until_ready`` alone provably
    returns early on the experimental 'axon' platform (round-1 recorded a
    physically impossible 6,500%-of-peak MFU that way).
  * Sanity gates: every loss must be finite, losses must CHANGE across
    steps (params are actually updating), and computed MFU must lie in
    (0, 1).  Violations print an error JSON and exit non-zero rather than
    recording garbage.

``vs_baseline`` is model-FLOPs utilization (MFU) against the chip's bf16
peak — the hardware-normalized analog of the reference's
scaling-efficiency metric (BASELINE.md: >=90% scaling efficiency target).
MFU uses 6*N_params FLOPs/token (attention FLOPs excluded — the standard,
conservative MFU convention).  The constants (PEAK_TFLOPS, the FLOPs
conventions) live in ``horovod_tpu/perf/costmodel.py`` — the perf
plane's single source of truth — and the artifact also carries the
attention-FLOPs-included ``mfu_attn`` variant (docs/profiling.md).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def _costmodel():
    """The perf plane's analytical cost model (horovod_tpu/perf/
    costmodel.py) — the ONE source of PEAK_TFLOPS and the FLOPs/token
    convention the MFU numbers are defined by.  Loaded BY FILE PATH so
    the supervisor stays free of the heavy package __init__ (the
    utils/probe.py pattern); the module is stdlib-only."""
    mod = sys.modules.get("horovod_tpu.perf.costmodel")
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "horovod_tpu", "perf", "costmodel.py")
        spec = importlib.util.spec_from_file_location(
            "horovod_tpu.perf.costmodel", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["horovod_tpu.perf.costmodel"] = mod
    return mod


# bf16 peak TFLOP/s per chip by TPU generation — re-exported from the
# cost model so existing callers keep the bench-level name.
PEAK_TFLOPS = _costmodel().PEAK_TFLOPS


def detect_chip() -> str:
    import os
    import jax
    kind = jax.devices()[0].device_kind.lower()
    plat = jax.devices()[0].platform.lower()
    if "cpu" in kind or plat == "cpu":
        return "cpu"
    # device_kind strings: 'TPU v4', 'TPU v5 lite' (v5e), 'TPU v5p', 'TPU v6e'
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return "v5e"
    for key in ("v6e", "v5p", "v4"):
        if key in kind:
            return key
    return os.environ.get("PALLAS_AXON_TPU_GEN", "") or "v5e"


def _init_with_retry(hvd, expect_tpu: bool, attempts: int = 3,
                     delay_s: float = 45.0) -> None:
    """Backend bring-up with retries: the remote-TPU tunnel can throw
    transient 'backend setup/compile error (Unavailable)'.

    jax caches backend-init state process-globally, so a naive re-call
    would re-raise the cached error — or worse, silently hand back an
    already-registered CPU backend and benchmark the wrong hardware.
    Each retry clears jax's backend cache first, and a successful init
    that landed on CPU when a TPU was expected counts as a failure."""
    import jax

    def clear_backends():
        try:
            from jax._src import xla_bridge
            xla_bridge._clear_backends()
        except Exception as e:  # private API moved: fail loudly, no retry
            raise RuntimeError(
                f"cannot clear jax backend cache for retry: {e}")

    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for i in range(attempts):
        try:
            hvd.init()
            if expect_tpu and jax.devices()[0].platform == "cpu":
                raise RuntimeError(
                    "Unavailable: TPU expected but jax fell back to the "
                    "CPU backend")
            return
        except RuntimeError as e:
            # case-insensitive: the tunnel emits mixed-case messages AND
            # canonical upper-case gRPC status prefixes ('UNAVAILABLE:')
            if "unavailable" not in str(e).lower() or i == attempts - 1:
                raise
            print(f"backend unavailable (attempt {i + 1}/{attempts}); "
                  f"retrying in {delay_s:.0f}s", file=sys.stderr)
            try:
                hvd.shutdown()
            except Exception as cleanup_err:
                # A partially-initialized runtime may fail its own
                # teardown; the retry must proceed anyway.
                print(f"shutdown during retry failed (ignored): "
                      f"{cleanup_err}", file=sys.stderr)
            clear_backends()
            time.sleep(delay_s)


def maybe_profile(args):
    """Context manager: a jax.profiler trace into ``args.profile`` when
    set, else a no-op.  One definition so every bench path opens the
    trace the same way."""
    import contextlib
    if not args.profile:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(args.profile)


def fail(reason: str, cause: str = "bench-crash", **extra) -> int:
    """Emit the error JSON.  ``cause`` is a closed taxonomy so artifacts
    distinguish infrastructure failures from real bench bugs (the r4
    flash-mxu rc=1 trio was unattributable without it):
    tunnel-down | tunnel-down-during-run | timeout | invalid-result |
    bench-crash | sanitized-lib."""
    print(json.dumps({"metric": "BENCH_INVALID", "value": 0,
                      "unit": "error", "vs_baseline": 0,
                      "cause": cause, "error": reason, **extra}))
    return 1


def classify_child_exit(rc) -> str:
    """Child exit status -> taxonomy label (the sweep-row / artifact
    counterpart of horovod_tpu.postmortem.classify_exit — duplicated so
    the bench supervisor stays importable without the package): a
    negative rc is a signal death, which is exactly the flash-crash
    attribution VERDICT r5 Weak #3 was missing behind a bare rc=1."""
    if rc is None:
        return "timeout"
    if rc == 0:
        return "clean"
    if rc < 0:
        import signal as _sig
        try:
            return f"signal:{_sig.Signals(-rc).name}"
        except ValueError:
            return f"signal:{-rc}"
    return f"error:rc={rc}"


def metrics_summary() -> dict:
    """Condensed `hvd.metrics_snapshot()` embedded in every bench JSON so
    artifact rows carry controller-level evidence (plan-cache hit rate,
    cycles, stall warnings) alongside MFU.  Best-effort: a bench number
    must never be lost to a telemetry hiccup."""
    try:
        import horovod_tpu as hvd
        fams = hvd.metrics_snapshot().get("families", {})

        def total(name):
            return sum(s.get("value", 0)
                       for s in fams.get(name, {}).get("samples", []))

        def rate(hit, miss):
            h, m = total(hit), total(miss)
            return round(h / (h + m), 4) if h + m else None

        # Watch plane (docs/watch.md): every alert that FIRED during the
        # run rides the artifact as (rule, severity, count), so a sweep
        # row records its in-flight incidents beside its MFU — a number
        # produced while `sentinel-nonfinite` fired reads differently.
        fired_alerts = []
        for s in fams.get("hvd_alerts_total", {}).get("samples", []):
            labels = s.get("labels", {})
            if s.get("value") and labels.get("rule"):
                fired_alerts.append({
                    "rule": labels["rule"],
                    "severity": labels.get("severity", "warning"),
                    "count": int(s["value"])})
        summary = {
            "schema": "hvd-metrics-summary-v1",
            "plan_cache_hit_rate": rate("hvd_fusion_plan_cache_hits_total",
                                        "hvd_fusion_plan_cache_misses_total"),
            "controller_cycles": int(total("hvd_controller_cycles_total")),
            "controller_cache_hit_rate": rate(
                "hvd_controller_cache_hits_total",
                "hvd_controller_cache_misses_total"),
            "collective_ops": int(total("hvd_collective_ops_total")),
            "collective_bytes": int(total("hvd_collective_bytes_total")),
            "stall_warnings": int(total("hvd_stall_warnings_total")),
            "fired_alerts": sorted(fired_alerts,
                                   key=lambda a: (a["rule"],
                                                  a["severity"])),
        }
        # When the run traced (HOROVOD_TIMELINE / --timeline-merge), the
        # artifact points at the evidence (docs/timeline.md).
        from horovod_tpu import runtime as _hvd_rt
        if _hvd_rt.is_initialized():
            tl = _hvd_rt.get().timeline
            if tl is not None:
                summary["timeline"] = tl.path
        return summary
    except Exception as e:
        return {"schema": "hvd-metrics-summary-v1", "error": str(e)}


def _enable_compile_cache(cpu: bool = False) -> None:
    """Persistent XLA compilation cache keyed on (program, flags): repeat
    bench invocations with the same config skip the ~3 min remote compile.
    Best-effort — an experimental backend may not support serialization.

    SKIPPED in cpu mode: XLA:CPU caches AOT results keyed without the
    exact host machine features, so an entry written on one machine
    loads on another with a "could lead to execution errors such as
    SIGILL" warning and can compute GARBAGE (observed: bitwise-constant
    losses -> BENCH_INVALID).  CPU compiles are seconds anyway; the
    cache exists for the ~3-45 min remote TPU compiles."""
    import jax
    if cpu:
        return
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_bench_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:
        print(f"compilation cache unavailable (ignored): {e}",
              file=sys.stderr)


def probe_tpu(timeout_s: float) -> str:
    """Killable-subprocess backend probe (round-2 recorded 25-minute
    in-process init hangs on a down tunnel).  The canonical
    implementation is the library's (also exposed as
    ``horovod_tpu.probe_backend``) — loaded here BY FILE PATH so the
    supervisor stays free of the heavy package __init__ (jax etc.), and
    any load failure degrades to a probe-failure string instead of
    killing the JSON contract."""
    try:
        mod = sys.modules.get("horovod_tpu.utils.probe")
        if mod is None:  # standalone supervisor: load the stdlib-only file
            import importlib.util
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "horovod_tpu", "utils", "probe.py")
            spec = importlib.util.spec_from_file_location(
                "horovod_tpu.utils.probe", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            # one module for repeat calls, tests, and the package import
            sys.modules["horovod_tpu.utils.probe"] = mod
        return mod.probe_backend(timeout_s)
    except Exception as e:
        # The probe is an optimization (fast-fail on a dead tunnel); a
        # broken loader must not veto a benchmark the deadline-bounded
        # child could still produce.
        print(f"probe unavailable, proceeding without it ({e})",
              file=sys.stderr)
        return ""


def supervise(argv) -> int:
    """Run the bench in a supervised child with a deadline, so a hung
    backend can never turn into silent rc=124: (1) fast probe fails to an
    error JSON in about a minute when the tunnel is down; (2) the full
    bench runs with a deadline; (3) on timeout, one reduced --steps
    fallback pass tries to land SOME valid number in the remaining budget.
    """
    t_start = time.monotonic()
    # Default sized to finish (incl. the --steps fallback) comfortably
    # inside the driver's observed ~30 min capture window — an rc=124
    # with no JSON is the one outcome this supervisor exists to prevent.
    deadline = float(os.environ.get("BENCH_DEADLINE_S", "1200"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "55"))

    # --scenario replays on a virtual clock (CPU by construction, even
    # when the spec says engine: real — that path forces JAX CPU); the
    # TPU probe would only block a mode that never touches the chip.
    scenario_mode = any(a.split("=", 1)[0] == "--scenario" for a in argv)
    if "--cpu" not in argv and not scenario_mode:
        reason = probe_tpu(probe_timeout)
        if reason:
            return fail(reason, cause="tunnel-down",
                        probe_timeout_s=probe_timeout)

    def run_child(extra_args, budget_s):
        """(json_line|None, status, exit_cause, stderr_tail).

        stderr is captured and re-emitted after the child exits: the
        console/nohup log keeps the full story while the last ~2 KB ride
        the artifact, so a crash leaves its traceback in the JSON row
        instead of scrolled off a console (VERDICT r5 Weak #3: three
        rounds of flash rows said `rc=1` and nothing else)."""
        cmd = [sys.executable, os.path.abspath(__file__), "--inner",
               *argv, *extra_args]
        try:
            res = subprocess.run(cmd, stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE, text=True,
                                 timeout=max(30.0, budget_s))
            rc, stderr = res.returncode, res.stderr or ""
        except subprocess.TimeoutExpired as e:
            rc = None
            stderr = (e.stderr.decode(errors="replace")
                      if isinstance(e.stderr, bytes) else (e.stderr or ""))
        if stderr:
            sys.stderr.write(stderr)
            sys.stderr.flush()
        stderr_tail = stderr[-2000:]
        if rc is None:
            return None, "timeout", "timeout", stderr_tail
        line = ""
        for ln in (res.stdout or "").strip().splitlines():
            if ln.startswith("{"):
                line = ln
        return (line or None), f"rc={rc}", classify_child_exit(rc), \
            stderr_tail

    # Reserve enough of the deadline that the --steps 10 fallback (guarded
    # on >120s below) is actually reachable when the full bench times out.
    remaining = deadline - (time.monotonic() - t_start)
    line, status, exit_cause, stderr_tail = run_child([], remaining - 180.0)
    if line:
        print(line)
        return 0 if "BENCH_INVALID" not in line else 1

    # Fallback: shorter scan (smaller timed window; the compile-cache may
    # also already hold this config from a prior round).
    remaining = deadline - (time.monotonic() - t_start)
    if remaining > 120.0 and "--steps" not in " ".join(argv):
        print(f"full bench failed ({status}); retrying with --steps 10 "
              f"({remaining:.0f}s left)", file=sys.stderr)
        line, status, exit_cause, stderr_tail = \
            run_child(["--steps", "10"], remaining - 15.0)
        if line:
            print(line)
            return 0 if "BENCH_INVALID" not in line else 1
    # Attribute the failure: a child that died (or hung — a dead tunnel
    # usually presents as a hang) while the tunnel dropped is an
    # infrastructure event, not a bench bug (the r4 flash-mxu trio was
    # ambiguous exactly here).  One <=55s probe on an already-failed
    # run is cheap.  The artifact carries the exit classification AND
    # the stderr tail so the next hardware window can attribute the
    # crash without re-reproducing it.
    cause = "timeout" if status == "timeout" else "bench-crash"
    if "--cpu" not in argv and not scenario_mode and \
            probe_tpu(probe_timeout):
        cause = "tunnel-down-during-run"
    return fail(f"bench child produced no JSON ({status})", cause=cause,
                exit_cause=exit_cause, stderr_tail=stderr_tail,
                elapsed_s=round(time.monotonic() - t_start, 1))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30,
                    help="timed steps (all inside one scan)")
    ap.add_argument("--batch", type=int, default=None,
                    help="per-chip batch (default: 16 llama / 64 resnet)")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--model", default="bench",
                    choices=["bench", "tiny", "mini", "1b", "8b"])
    ap.add_argument("--resnet", action="store_true",
                    help="ResNet images/sec/chip instead of the llama "
                         "tokens/sec (the reference's headline metric: "
                         "docs/benchmarks.rst ResNet img/sec)")
    ap.add_argument("--cnn", default=None,
                    choices=["resnet50", "resnet101", "vgg16", "inception3"],
                    help="CNN images/sec family — the reference's full "
                         "headline-table trio (docs/benchmarks.rst:12-13 "
                         "Inception V3 / ResNet / VGG-16); --resnet is the "
                         "back-compat spelling of resnet{--depth}")
    ap.add_argument("--depth", type=int, default=50, choices=[50, 101],
                    help="ResNet depth; 101 matches the reference's "
                         "1656.82 img/s 16-GPU headline row exactly")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize the forward pass (bigger batches)")
    ap.add_argument("--fuse", action="store_true",
                    help="enable the fused qkv/gate-up projections "
                         "(measured SLOWER than unfused on v5e: 0.423 vs "
                         "0.437 MFU, sweep_results.jsonl fused-default vs "
                         "default-b16 — so the bench default is unfused)")
    ap.add_argument("--no-fuse", action="store_true",
                    help="back-compat no-op: unfused is the default; "
                         "kept so recorded sweep configs stay runnable")
    ap.add_argument("--scan-unroll", type=int, default=1,
                    help="lax.scan unroll factor for the timed step loop "
                         "(unrolled iterations drop loop overhead and let "
                         "XLA overlap across step boundaries; program "
                         "size grows proportionally)")
    ap.add_argument("--ce-chunks", type=int, default=0,
                    help="stream the lm_head+cross-entropy over N sequence "
                         "chunks under jax.checkpoint (0 = whole-sequence "
                         "logits); cuts the ~1 GB logits slab to 1/N live")
    ap.add_argument("--dim", type=int, default=0,
                    help="override model width (with --layers/--ffn, scans "
                         "custom shapes; 0 = use --model's config)")
    ap.add_argument("--layers", type=int, default=0)
    ap.add_argument("--ffn", type=int, default=0)
    ap.add_argument("--score-dtype", default=None,
                    choices=["f32", "input"],
                    help="dtype the attention score tensor materializes "
                         "in (XLA attention path).  'input' (default "
                         "since 2026-08-01: 0.540 vs 0.437 MFU measured, "
                         "identical loss trajectory — sweep rows "
                         "nofuse-score-input / nofuse-control) halves "
                         "the score-slab HBM traffic for bf16 models; "
                         "f32 keeps full logit precision")
    ap.add_argument("--flash", action="store_true",
                    help="use the pallas flash-attention kernel (forward "
                         "is ~1.3x XLA's, but compiling it inside the "
                         "scanned step is slow on remote-compile setups)")
    ap.add_argument("--block-q", type=int, default=256,
                    help="flash attention q-block (VMEM tuning)")
    ap.add_argument("--block-k", type=int, default=256,
                    help="flash attention k-block (VMEM tuning)")
    ap.add_argument("--scaling", action="store_true",
                    help="weak-scaling efficiency over mesh prefixes "
                         "{1,2,4,...} — the reference's headline metric "
                         "(docs/benchmarks.rst 90%% at 512 GPUs); needs "
                         "multi-chip (or the CPU-virtual mesh) to be "
                         "non-trivial")
    ap.add_argument("--autotune", action="store_true",
                    help="HOROVOD_AUTOTUNE end-to-end: tune (fusion "
                         "threshold, cycle) on the live fused gradient "
                         "sync, log the trajectory to "
                         "HOROVOD_AUTOTUNE_LOG, report before/after "
                         "sync throughput")
    ap.add_argument("--wire", action="store_true",
                    help="wire-policy sweep (ops/wire.py): run the fused "
                         "sync under each wire policy on a model-like "
                         "bucket mix and emit a per-policy {wire_bytes/"
                         "step, step_time, residual_norm} comparison "
                         "artifact with decode-determinism asserted")
    ap.add_argument("--overlap", action="store_true",
                    help="overlap-plane sweep (ops/overlap.py): run the "
                         "microbatch-pipelined step at each depth and "
                         "the bucket-interleaved ZeRO-1 step, emitting "
                         "per-depth {step_time, exposed_comm_bytes "
                         "(analytical), overlapped_fraction} with the "
                         "pipelined ≡ sequential params guard asserted")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO weight-update sharding sweep "
                         "(parallel/zero.py; docs/zero.md): run the "
                         "chain at levels 0-3 on the quadratic toy and "
                         "llama-tiny, emitting per-level {analytical "
                         "peak params+grads+opt-state bytes, step_time, "
                         "exposed_comm_bytes, ledger model drift} with "
                         "level 1/2/3 bit-near equivalence asserted")
    ap.add_argument("--layout", action="store_true",
                    help="3D layout sweep (parallel/layout.py + "
                         "perf/costmodel solver; docs/parallelism.md): "
                         "solve the (dp, tp, pp) candidate table for "
                         "llama-tiny, then RUN every candidate mesh "
                         "through the composed TP x PP x ZeRO chain, "
                         "emitting per-layout {measured step_time, "
                         "measured peak bytes, solver-predicted step + "
                         "memory, predicted-vs-measured drift} with "
                         "cross-layout bit-near equivalence asserted")
    ap.add_argument("--serve", action="store_true",
                    help="serving load-generator sweep (serve/engine.py; "
                         "docs/serving.md): drive the continuous-"
                         "batching engine closed-loop (fixed concurrent "
                         "users) and with Poisson arrivals, emitting "
                         "{throughput_tok_s, ttft_p50/p99, tpot_p50/p99, "
                         "batch_fill} per mode, CPU-virtual labeled")
    ap.add_argument("--users", nargs="?", const="1,2,4,8,16,24",
                    default=None, metavar="N,N,...",
                    help="with --serve: control-plane saturation sweep "
                         "(docs/control-plane.md) — closed-loop user "
                         "pools of each size drive POST /generate "
                         "through the REAL router + rendezvous KV with "
                         "a scripted fixed-cost engine, locating the "
                         "router/KV throughput knee for the single-"
                         "process baseline vs the sharded + direct-"
                         "stream control plane (default sweep "
                         "1,2,4,8,16,24)")
    ap.add_argument("--replicas", nargs="?", const="1,2,4",
                    default=None, metavar="N,N,...",
                    help="with --serve --users: replica scale-out sweep "
                         "(docs/serving.md#replicated-tier) — repeat the "
                         "user-count sweep against N independent replica "
                         "fleets registered behind one router with "
                         "prefix-affinity routing, locating the knee per "
                         "replica count plus the affinity hit rate vs "
                         "the least-loaded-only baseline (default sweep "
                         "1,2,4)")
    ap.add_argument("--scenario", metavar="SPEC_YAML", default=None,
                    help="deterministic scenario replay "
                         "(horovod_tpu/scenario; docs/scenarios.md): "
                         "run the spec's trace + fault storm against "
                         "the real router/watch planes on a virtual "
                         "clock, twice — byte-identical SLO rows are "
                         "the validity gate — then once against a live "
                         "rendezvous server whose GET /alerts is "
                         "checked against the spec's expect_alerts; "
                         "per-scenario rows ride the artifact as "
                         "sub_rows for perf/gate.py")
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (smoke mode)")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the timed scan "
                         "into DIR (inspect with xprof/tensorboard to see "
                         "where step time goes)")
    ap.add_argument("--inner", action="store_true",
                    help="internal: run the measurement directly (no "
                         "probe/deadline supervisor)")
    args = ap.parse_args()
    # Resolve the score-dtype default BEFORE any mode dispatch so every
    # mode (throughput, --scaling, ...) sees the same resolved protocol.
    # Explicitness is remembered for the --flash conflict warning below.
    args.score_dtype_explicit = args.score_dtype is not None
    if args.score_dtype is None:
        args.score_dtype = "input"

    # Sanitizer guard (docs/static-analysis.md): a TSan/ASan/UBSan build
    # of the native core is 5-20x slower — its numbers are correctness
    # evidence, never performance evidence, so every bench artifact path
    # refuses it outright rather than emitting a poisoned row the perf
    # gate would later baseline against.  Checked only when
    # HOROVOD_NATIVE_LIB overrides the default: the default library is
    # always a plain build, so the common case pays nothing.
    if os.environ.get("HOROVOD_NATIVE_LIB", ""):
        from horovod_tpu.common.basics import native_build_info
        san = native_build_info().get("sanitizer", "none")
        if san != "none":
            return fail(
                f"HOROVOD_NATIVE_LIB is a {san} sanitizer build; bench "
                "artifacts from a sanitized library are invalid by "
                "construction (docs/static-analysis.md)",
                cause="sanitized-lib")

    if not args.inner:
        return supervise([a for a in sys.argv[1:] if a != "--inner"])

    # The flash kernel never materializes a score tensor, so an EXPLICIT
    # --score-dtype (either value) cannot combine with --flash; labeling
    # such a row with a score dtype would record a measurement of nothing
    # (ADVICE r3; symmetry + every-mode coverage ADVICE r5 #1).  Hoisted
    # above the mode dispatch so --scaling runs warn too; the resolved
    # default stays silent.
    if args.flash and not args.cpu and args.score_dtype_explicit:
        print(f"--score-dtype {args.score_dtype} is ignored under --flash "
              "(the kernel has no score tensor)", file=sys.stderr)

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.scenario:
        # Virtual-clock replay: no jax import unless the spec says
        # engine: real, and even then the replay is CPU by construction.
        os.environ["JAX_PLATFORMS"] = "cpu"
        return scenario_bench(args)
    if (args.wire or args.overlap or args.zero or args.layout) \
            and args.cpu and \
            "xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        # The wire/overlap/zero/layout sweeps are about collectives:
        # virtualize an 8-device CPU mesh (the test harness's topology)
        # so the rings actually ring.  Scoped here: the other cpu
        # smokes keep their 1-device runs.
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_"
                                   "count=8").strip()
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache(cpu=args.cpu)
    import jax.numpy as jnp
    import optax

    if args.scaling:
        return scaling_bench(args)
    if args.wire:
        if args.profile:
            print("--profile is not supported with --wire (one trace per "
                  "policy would overwrite itself); ignoring",
                  file=sys.stderr)
        return wire_bench(args)
    if args.overlap:
        if args.profile:
            print("--profile is not supported with --overlap (one trace "
                  "per depth would overwrite itself); ignoring",
                  file=sys.stderr)
        return overlap_bench(args)
    if args.zero:
        if args.profile:
            print("--profile is not supported with --zero (one trace per "
                  "level would overwrite itself); ignoring",
                  file=sys.stderr)
        return zero_bench(args)
    if args.layout:
        if args.profile:
            print("--profile is not supported with --layout (one trace "
                  "per candidate mesh would overwrite itself); ignoring",
                  file=sys.stderr)
        return layout_bench(args)
    if args.serve:
        if args.profile:
            print("--profile is not supported with --serve (the tick "
                  "loop is not one scanned program); ignoring",
                  file=sys.stderr)
        if args.users:
            # Control-plane saturation sweep: scripted engine, no jax
            # compute — the measurement is the router+KV, not decode.
            if args.replicas:
                return serve_replicas_bench(args)
            return serve_users_bench(args)
        if args.replicas:
            print("--replicas needs --users (the replica sweep rides "
                  "the control-plane saturation harness)",
                  file=sys.stderr)
            return 2
        return serve_bench(args)
    if args.autotune:
        if args.profile:
            print("--profile is not supported with --autotune (its timing "
                  "loop re-traces per threshold); ignoring", file=sys.stderr)
        return autotune_bench(args)
    if args.resnet or args.cnn:
        return resnet_bench(args)
    if args.batch is None:
        args.batch = 16

    import horovod_tpu as hvd
    from horovod_tpu.models import llama
    from horovod_tpu.parallel.data_parallel import (make_scanned_train_step,
                                                    replicate, shard_batch)

    # ~350M-param decoder: big enough to keep the MXU busy on one chip,
    # small enough to compile fast and fit HBM with optimizer state.
    cfgs = dict(llama.CONFIGS)
    cfgs["bench"] = llama.LlamaConfig(
        vocab=32768, dim=1024, n_layers=8, n_heads=16, n_kv_heads=8,
        ffn_dim=4096, max_seq=max(2048, args.seq),
        dtype=jnp.bfloat16)
    import dataclasses
    cfg = dataclasses.replace(cfgs[args.model],
                              fuse_proj=args.fuse and not args.no_fuse)
    if args.dim:
        cfg = dataclasses.replace(
            cfg, dim=args.dim,
            n_layers=args.layers or cfg.n_layers,
            n_heads=max(1, args.dim // 64),
            n_kv_heads=max(1, args.dim // 128),
            ffn_dim=args.ffn or 4 * args.dim)
    if args.cpu:
        cfg = llama.CONFIGS["tiny"]
        args.batch, args.seq, args.steps = 4, 64, 4

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    mesh = hvd.mesh()
    n_chips = hvd.size()

    params = llama.init(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    opt = optax.adamw(3e-4, weight_decay=0.01)
    # Pallas flash attention on TPU (ops/flash_attention.py): blockwise
    # online softmax on the MXU, ~1.3x the XLA attention at seq 1024.
    attn_fn = None
    if args.flash and not args.cpu:
        import functools
        from horovod_tpu.ops.flash_attention import flash_attention
        attn_fn = functools.partial(flash_attention,
                                    block_q=args.block_q,
                                    block_k=args.block_k)
    elif args.score_dtype == "input":
        import functools
        from horovod_tpu.models import layers as L
        attn_fn = functools.partial(L.causal_attention, score_dtype=None)

    # --remat uses the model's PER-LAYER checkpointing (the standard TPU
    # memory lever); whole-loss jax.checkpoint wouldn't reduce the peak.
    run = make_scanned_train_step(
        lambda p, ids: llama.loss_fn(p, ids, cfg, attn_fn=attn_fn,
                                     remat=args.remat,
                                     ce_chunks=args.ce_chunks),
        opt, mesh, unroll=args.scan_unroll)
    params = replicate(params, mesh)
    opt_state = replicate(opt.init(params), mesh)

    global_batch = args.batch * n_chips
    rng = np.random.RandomState(0)

    def make_batches(k: int):
        ids = rng.randint(0, cfg.vocab, (k, global_batch, args.seq + 1),
                          dtype=np.int32)
        return shard_batch(jnp.asarray(ids), mesh, axis=1)

    # Warmup: compile + one real run at the SAME scan length as the timed
    # call (a different K would retrace, putting XLA compilation inside the
    # timed window), fenced by a host fetch.
    wparams, wopt, wlosses = run(params, opt_state, make_batches(args.steps))
    warm = np.asarray(wlosses)  # D2H fence
    if not np.all(np.isfinite(warm)):
        return fail("non-finite warmup loss", cause="invalid-result",
                    losses=warm.tolist())
    params, opt_state = wparams, wopt

    batches = make_batches(args.steps)
    with maybe_profile(args):
        t0 = time.perf_counter()
        params, opt_state, losses = run(params, opt_state, batches)
        losses_host = np.asarray(losses)  # D2H fence — timer is honest
        dt = time.perf_counter() - t0

    # --- sanity gates ---------------------------------------------------
    if losses_host.shape != (args.steps,):
        return fail("loss shape mismatch", cause="invalid-result",
                    shape=list(losses_host.shape))
    if not np.all(np.isfinite(losses_host)):
        return fail("non-finite loss in timed run", cause="invalid-result",
                    losses=losses_host.tolist())
    if args.steps > 1 and float(np.ptp(losses_host)) == 0.0:
        return fail("loss constant across steps — params not updating",
                    cause="invalid-result",
                    loss=float(losses_host[0]))

    tokens = args.steps * global_batch * args.seq
    tok_per_sec = tokens / dt
    tok_per_sec_chip = tok_per_sec / n_chips

    chip = detect_chip()
    cm = _costmodel()
    peak = cm.peak_flops(chip)
    # The conservative 6N convention headlines; the attention-inclusive
    # variant rides beside it (mfu_attn — convention documented in
    # horovod_tpu/perf/costmodel.py train_flops_per_token).
    train_flops_per_token = cm.train_flops_per_token(n_params)
    mfu = (tok_per_sec_chip * train_flops_per_token) / peak
    mfu_attn = (tok_per_sec_chip * cm.train_flops_per_token(
        n_params, attention=dict(n_layers=cfg.n_layers, dim=cfg.dim,
                                 seq=args.seq, causal=True))) / peak

    if not (0.0 < mfu < 1.0):
        return fail(
            f"MFU {mfu:.4f} outside (0,1) — timing or peak detection broken",
            cause="invalid-result",
            chip=chip, tok_per_sec_chip=tok_per_sec_chip,
            loss_first=float(losses_host[0]), loss_last=float(losses_host[-1]))

    print(json.dumps({
        "metric": f"llama-{n_params/1e6:.0f}M train tokens/sec/chip "
                  f"({chip}, bf16, seq={args.seq}, "
                  f"loss {float(losses_host[0]):.3f}->"
                  f"{float(losses_host[-1]):.3f})",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        # One schema, one meaning: vs_baseline IS the MFU for model
        # benches; mfu/vs_baseline_is make that explicit in the artifact
        # (a 65x-of-peak artifact can never masquerade as MFU again).
        "mfu": round(mfu, 4),
        # Attention-FLOPs-included MFU (6N + 6·L·seq·dim causal term,
        # perf/costmodel.py): higher than `mfu` by construction; the
        # conservative 6N number stays the headline/vs_baseline.
        "mfu_attn": round(mfu_attn, 4),
        "vs_baseline_is": "mfu",
        "vs_baseline": round(mfu, 4),
        # Self-describing protocol: which attention path actually ran,
        # so an artifact row never depends on remembering what the
        # bench default was the day it was recorded.
        "attn": ("flash" if (args.flash and not args.cpu)
                 else f"xla-score-{args.score_dtype}"),
        # Controller-level evidence riding the artifact (docs/metrics.md).
        "metrics": metrics_summary(),
    }))
    return 0


def scaling_bench(args) -> int:
    """Weak-scaling efficiency over mesh prefixes — the REFERENCE'S
    headline metric (docs/benchmarks.rst:12-43 publishes 90%/90%/68%
    scaling efficiency at 512 GPUs; BASELINE.md targets >=90% on
    v5p-128).  Per-chip batch is held fixed while the data mesh grows
    over device prefixes {1, 2, 4, ...}; efficiency(k) = per-chip
    throughput at k chips / per-chip throughput at 1 chip.  On the
    single-tunnel chip this degenerates to k=1 (the mode exists for
    multi-chip hardware; the CPU-virtual harness proves the machinery
    and measures the DP path's real collective overhead)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    import horovod_tpu as hvd
    from horovod_tpu.models import llama
    from horovod_tpu.parallel.data_parallel import (make_scanned_train_step,
                                                    replicate, shard_batch)

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    devices = jax.devices()
    sizes = [k for k in (1, 2, 4, 8, 16, 32, 64, 128, 256)
             if k <= len(devices)]
    import dataclasses
    if args.cpu:
        cfg = llama.CONFIGS["tiny"]
        batch, seq, steps = 4, 64, 6
    else:
        cfg = llama.CONFIGS[args.model] if args.model != "bench" else \
            llama.LlamaConfig(vocab=32768, dim=1024, n_layers=8,
                              n_heads=16, n_kv_heads=8, ffn_dim=4096,
                              max_seq=max(2048, args.seq),
                              dtype=jnp.bfloat16)
        batch, seq, steps = (args.batch or 16), args.seq, args.steps
    # The perf levers mean the same thing here as in the throughput
    # bench: an efficiency labeled with a flag must have run it.
    cfg = dataclasses.replace(cfg, fuse_proj=args.fuse and not args.no_fuse)
    attn_fn = None
    if args.flash and not args.cpu:
        import functools
        from horovod_tpu.ops.flash_attention import flash_attention
        attn_fn = functools.partial(flash_attention, block_q=args.block_q,
                                    block_k=args.block_k)
    elif args.score_dtype == "input":
        import functools
        from horovod_tpu.models import layers as L
        attn_fn = functools.partial(L.causal_attention, score_dtype=None)
    if args.profile:
        print("--profile is ignored under --scaling (one trace per mesh "
              "size would overwrite itself)", file=sys.stderr)
    opt = optax.adamw(3e-4, weight_decay=0.01)
    base_params = llama.init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)

    rates = {}
    axis = hvd.mesh().axis_names[0]  # train step syncs over this name
    for k in sizes:
        mesh = Mesh(np.asarray(devices[:k]), (axis,))
        run = make_scanned_train_step(
            lambda p, ids: llama.loss_fn(p, ids, cfg, attn_fn=attn_fn,
                                         remat=args.remat,
                                         ce_chunks=args.ce_chunks),
            opt, mesh, axis_name=axis, unroll=args.scan_unroll)
        params = replicate(base_params, mesh)
        opt_state = replicate(opt.init(params), mesh)

        def make_batches():
            ids = rng.randint(0, cfg.vocab, (steps, batch * k, seq + 1),
                              dtype=np.int32)
            return shard_batch(jnp.asarray(ids), mesh,
                               axis_name=axis, axis=1)

        # compile + warm outside the timed window, fenced by a host fetch
        params, opt_state, wl = run(params, opt_state, make_batches())
        if not np.all(np.isfinite(np.asarray(wl))):
            return fail(f"non-finite warmup loss at {k} chips",
                        cause="invalid-result")
        batches = make_batches()
        t0 = time.perf_counter()
        params, opt_state, losses = run(params, opt_state, batches)
        losses_host = np.asarray(losses)  # D2H fence — timer is honest
        dt = time.perf_counter() - t0
        if not np.all(np.isfinite(losses_host)):
            return fail(f"non-finite loss at {k} chips",
                        cause="invalid-result")
        if steps > 1 and float(np.ptp(losses_host)) == 0.0:
            return fail(f"loss constant across steps at {k} chips — "
                        "params not updating", cause="invalid-result")
        # per-chip tok/s (global tokens / dt / k == steps*batch*seq/dt)
        rates[k] = steps * batch * seq / dt

    top = sizes[-1]
    eff = rates[top] / rates[1] if top > 1 else 1.0
    if not (0.0 < eff <= 1.5):  # >1 = measurement noise beyond sanity
        return fail(f"scaling efficiency {eff:.3f} implausible",
                    cause="invalid-result", rates=rates)
    chip = detect_chip()
    per_size = ", ".join(f"{k}: {rates[k]:,.0f}" for k in sizes)
    print(json.dumps({
        "metric": (f"llama weak-scaling efficiency at {top} chips vs 1 "
                   f"({chip}, per-chip batch {batch}, seq {seq}; "
                   f"per-chip tok/s by size: {per_size})"),
        "value": round(eff, 4),
        "unit": "scaling_efficiency",
        "vs_baseline_is": "weak_scaling_efficiency_vs_1chip",
        "vs_baseline": round(eff, 4),
        "rates_tok_s_chip": {str(k): round(v, 1)
                             for k, v in rates.items()},
        "attn": ("flash" if (args.flash and not args.cpu)
                 else f"xla-score-{args.score_dtype}"),
        "metrics": metrics_summary(),
    }))
    return 0


def autotune_bench(args) -> int:
    """Autotune proven end to end (reference: parameter_manager.{h,cc}
    scoring loop): the fused gradient sync runs under the live autotuner,
    every accepted (threshold, cycle) sample re-traces the bucket plan,
    the trajectory lands in HOROVOD_AUTOTUNE_LOG, and the JSON reports
    the tuned threshold plus after/before sync-throughput ratio."""
    os.environ["HOROVOD_AUTOTUNE"] = "1"
    log_path = os.environ.setdefault("HOROVOD_AUTOTUNE_LOG",
                                     "autotune_log.csv")
    os.environ.setdefault("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    os.environ.setdefault("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    import jax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops._compat import shard_map
    from horovod_tpu.optimizer import sync_gradients

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    mesh = hvd.mesh()
    axis = mesh.axis_names[0]
    n = hvd.size()
    tuner = hvd.autotuner()
    if tuner is None:
        return fail("HOROVOD_AUTOTUNE=1 did not enable the autotuner",
                    cause="invalid-result")

    # A model-like gradient set: a few big tensors + a tail of small ones
    # (what makes bucketing matter).  ~100 MB on TPU, ~2 MB on CPU.
    rng = np.random.RandomState(0)
    per = 128 if args.cpu else 8192
    gs = ([rng.randn(n, per * 16).astype(np.float32) for _ in range(12)] +
          [rng.randn(n, per).astype(np.float32) for _ in range(24)] +
          [rng.randn(n, 16).astype(np.float32) for _ in range(24)])
    total = sum(g.nbytes // n for g in gs)

    compiled = {}

    def step_fn(threshold: int):
        fn = compiled.get(threshold)
        if fn is None:
            def body(*leaves):
                return tuple(sync_gradients(
                    list(leaves), axis,
                    fusion_threshold_bytes=threshold))
            fn = jax.jit(shard_map(
                body, mesh=mesh, in_specs=(P(axis),) * len(gs),
                out_specs=(P(axis),) * len(gs), check_vma=False))
            compiled[threshold] = fn
        return fn

    def timed_sync(threshold: int, steps: int = 5) -> float:
        """bytes/sec of the fused sync at a given threshold."""
        fn = step_fn(threshold)
        jax.block_until_ready(fn(*gs))  # compile outside the timing
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*gs)
        jax.block_until_ready(out)
        return steps * total / (time.perf_counter() - t0)

    initial = tuner.fusion_threshold
    steps = 0
    while not tuner.done and steps < 120:
        thr = tuner.fusion_threshold
        fresh = thr not in compiled
        fn = step_fn(thr)
        if fresh:
            # compile OUTSIDE the measurement: a candidate scored with
            # its one-time trace+compile cost inside the window would
            # always lose to the warmed-up incumbent
            jax.block_until_ready(fn(*gs))
        with tuner.measure(nbytes=total):
            jax.block_until_ready(fn(*gs))
        steps += 1
    if not tuner.done:
        return fail(f"autotune did not converge in {steps} steps",
                    cause="invalid-result")
    tuned = tuner.fusion_threshold

    before = timed_sync(initial)
    after = timed_sync(tuned)
    print(json.dumps({
        "metric": f"autotune fused-sync GB/s (tuned threshold "
                  f"{tuned / (1 << 20):.1f} MiB vs initial "
                  f"{initial / (1 << 20):.0f} MiB, {steps} steps, "
                  f"log={log_path})",
        "value": round(after / 1e9, 3),
        "unit": "GB/s",
        "vs_baseline_is": "speedup_vs_initial_threshold",
        "vs_baseline": round(after / max(before, 1e-9), 4),
        "metrics": metrics_summary(),
    }))
    return 0


def wire_bench(args) -> int:
    """Wire-policy sweep (ops/wire.py; docs/tensor-fusion.md): the fused
    gradient sync runs under each wire policy on a model-like bucket mix
    (a few big tensors + a long small tail), with EF residuals carried
    step to step.  Per policy the artifact records the MODELED per-chip
    wire bytes/step (the analytical ring model — on the CPU-virtual
    harness there is no physical wire to count), the measured step time,
    and the per-bucket EF residual norms; every policy's decode is
    asserted bit-identical across ranks.  A second section re-initializes
    a two-level (dcn, ici) mesh and compares dcn_int8's DCN-leg bytes
    against the flat int8 ring's."""
    import jax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.common.reduce_op import Average
    from horovod_tpu.ops import wire
    from horovod_tpu.ops._compat import shard_map
    from horovod_tpu.ops.fusion import make_plan
    from horovod_tpu.optimizer import sync_gradients_ef, \
        wire_residual_report

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    n = hvd.size()
    timed_steps = 5 if args.cpu else 20

    # Model-like gradient mix: bucket sizes must straddle the auto
    # policy's thresholds so 'auto' demonstrably picks PER-BUCKET formats
    # (big buckets -> int8 ring, the mid tail -> bf16).
    rng = np.random.RandomState(0)
    per = 8192
    gs = [rng.randn(n, per * 16).astype(np.float32) for _ in range(12)] + \
         [rng.randn(n, per).astype(np.float32) for _ in range(24)] + \
         [rng.randn(n, 16).astype(np.float32) for _ in range(24)]
    threshold = 4 * 1024 * 1024
    # The per-rank leaf shapes the sync sees inside shard_map.
    shard_shapes = [(1, g.shape[1]) for g in gs]
    dtypes = [g.dtype for g in gs]
    plan = make_plan(shard_shapes, dtypes, threshold)
    exact = [g.mean(axis=0) for g in gs]

    def modeled_bytes(policy_name, axis_name, axis_sizes):
        pol = wire.get_policy(policy_name)
        total, per_fmt = 0.0, {}
        for b in plan.buckets:
            fmt = wire.resolve_format(pol(b.nbytes, b.dtype, axis_name),
                                      b.dtype, axis_name, Average)
            m = wire.modeled_wire_bytes(sum(b.sizes),
                                        np.dtype(b.dtype).itemsize,
                                        fmt, axis_sizes)
            total += m["bottleneck"]
            per_fmt[fmt] = per_fmt.get(fmt, 0.0) + m["bottleneck"]
        return int(total), {k: int(v) for k, v in sorted(per_fmt.items())}

    def run_policy(policy_name, mesh, axis_name, axis_spec):
        specs = (tuple(P(*axis_spec) for _ in gs),) * 2

        def body(leaves, res):
            s, r = sync_gradients_ef(list(leaves), list(res), axis_name,
                                     fusion_threshold_bytes=threshold,
                                     wire_policy=policy_name)
            return tuple(s), tuple(r)

        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=specs,
                               out_specs=specs, check_vma=False))
        res = tuple(np.zeros_like(g) for g in gs)
        leaves = tuple(gs)
        out, res = fn(leaves, res)   # compile + warm outside the timing
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            out, res = fn(leaves, res)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / timed_steps
        # decode determinism: every rank must hold identical values
        for o in out:
            rows = np.asarray(o)
            for r in range(1, rows.shape[0]):
                if not np.array_equal(rows[r], rows[0]):
                    raise AssertionError(
                        f"policy {policy_name}: rank {r} decoded "
                        "different values than rank 0")
        # accuracy guard: still a mean within the formats' noise
        err = max(float(np.abs(np.asarray(o)[0] - e).max())
                  for o, e in zip(out, exact))
        if err > 0.1:
            raise AssertionError(
                f"policy {policy_name}: error {err} vs exact mean")
        norms = wire_residual_report([np.asarray(r) for r in res],
                                     plan=plan)
        return dt, err, {k: round(v, 6) for k, v in norms.items()
                         if v > 0.0}

    mesh = hvd.mesh()
    axis = mesh.axis_names[0]
    policies = ["none", "bf16", "fp16", "int8_ring", "auto"]
    results = {}
    try:
        for name in policies:
            wire_bytes, per_fmt = modeled_bytes(name, axis, {"flat": n})
            dt, err, norms = run_policy(name, mesh, axis, (axis,))
            results[name] = {
                "wire_bytes_per_step": wire_bytes,
                "wire_bytes_by_format": per_fmt,
                "step_time_s": round(dt, 6),
                "max_abs_err": round(err, 6),
                "residual_norm": norms,
                "decode_deterministic": True,
            }
    except AssertionError as e:
        return fail(str(e), cause="invalid-result")

    # Acceptance ratios on the bucket mix (ISSUE 3): int8 carries <= 1/2
    # the modeled wire bytes of bf16, <= 1/4 of uncompressed fp32.
    b_none = results["none"]["wire_bytes_per_step"]
    b_bf16 = results["bf16"]["wire_bytes_per_step"]
    b_int8 = results["int8_ring"]["wire_bytes_per_step"]
    if not (b_int8 * 2 <= b_bf16 and b_int8 * 4 <= b_none):
        return fail(f"int8 wire bytes {b_int8} not <= bf16/2 "
                    f"({b_bf16}) and fp32/4 ({b_none})",
                    cause="invalid-result")

    # Two-level section: dcn_int8 quantizes only the slow leg.  The CPU
    # harness re-initializes the same 8 virtual devices as a 2x4
    # (dcn, ici) mesh; on hardware this needs a multi-slice mesh.
    two_level = {}
    if n % 2 == 0 and n >= 4:
        hvd.shutdown()
        hvd.init(mesh_spec=f"dcn.data=2,ici.data={n // 2}")
        mesh2 = hvd.mesh()
        axis2 = ("dcn.data", "ici.data")
        sizes2 = {"dcn": 2, "ici": n // 2}
        try:
            for name in ("int8_ring", "dcn_int8"):
                wire_bytes, per_fmt = modeled_bytes(name, axis2, sizes2)
                dt, err, norms = run_policy(name, mesh2, axis2, (axis2,))
                two_level[name] = {
                    "dcn_wire_bytes_per_step": wire_bytes,
                    "step_time_s": round(dt, 6),
                    "max_abs_err": round(err, 6),
                    "residual_norm": norms,
                    "decode_deterministic": True,
                }
        except AssertionError as e:
            return fail(str(e), cause="invalid-result")
        d_flat = two_level["int8_ring"]["dcn_wire_bytes_per_step"]
        d_sel = two_level["dcn_int8"]["dcn_wire_bytes_per_step"]
        if d_sel >= d_flat:
            return fail(f"dcn_int8 DCN bytes {d_sel} not below the flat "
                        f"int8 ring's {d_flat}", cause="invalid-result")

    chip = detect_chip()
    label = (f"CPU-virtual ({n} XLA host devices, loopback; no chip, no "
             "host<->device — wire bytes are the analytical ring model)"
             if chip == "cpu" else chip)
    print(json.dumps({
        "metric": f"wire-policy sweep: int8 ring carries "
                  f"{b_int8 / b_none:.3f}x the modeled wire bytes of "
                  f"fp32 ({b_int8 / b_bf16:.3f}x bf16) on the "
                  f"{plan.num_buckets}-bucket mix [{label}]",
        "value": round(b_int8 / b_none, 4),
        "unit": "wire_bytes_ratio_int8_vs_fp32",
        "vs_baseline_is": "modeled_wire_bytes_int8_over_fp32",
        "vs_baseline": round(b_int8 / b_none, 4),
        "label": label,
        "policies": results,
        "two_level": two_level,
        "metrics": metrics_summary(),
    }))
    return 0


def overlap_bench(args) -> int:
    """Overlap-plane sweep (ops/overlap.py; docs/overlap.md): the
    microbatch-pipelined train step runs at depth 0 (the sequential
    issue order of the same per-microbatch syncs), 1 and 2, plus the
    legacy accumulate-k-then-sync baseline ('off'); the bucket-
    interleaved ZeRO-1 step runs against the monolithic chain.  Per row
    the artifact records the measured step time and the ANALYTICAL
    {exposed_comm_bytes, overlapped_fraction} split (the hvd_overlap_*
    gauge model — on the CPU-virtual harness there is no latency-hiding
    scheduler, so wall-clock parity is expected and only the schedule
    is being proven; wins need a real TPU).  The pipelined ≡ sequential
    params guarantee is asserted per depth before anything is printed."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.parallel.data_parallel import (
        make_microbatched_train_step, replicate, shard_batch)
    from horovod_tpu.parallel.zero import (init_sharded_opt_state,
                                           make_zero1_train_step)
    from horovod_tpu.utils import metrics as M

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    mesh = hvd.mesh()
    n = hvd.size()
    k = 4
    timed_steps = 5 if args.cpu else 20
    dim = 64 if args.cpu else 1024

    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(dim, dim) / np.sqrt(dim),
                                jnp.float32),
              "b1": jnp.asarray(np.zeros(dim), jnp.float32),
              "w2": jnp.asarray(rng.randn(dim, 1) / np.sqrt(dim),
                                jnp.float32)}

    def loss_fn(p, batch):
        x, y = batch
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)

    xs = rng.randn(k, 8 * n, dim).astype(np.float32)
    ys = rng.randn(k, 8 * n, 1).astype(np.float32)
    batch = (shard_batch(jnp.asarray(xs), mesh, axis=1),
             shard_batch(jnp.asarray(ys), mesh, axis=1))

    grad_bytes = sum(int(np.prod(l.shape)) * 4
                     for l in jax.tree_util.tree_leaves(params))
    from horovod_tpu.ops.wire import modeled_wire_bytes
    per_sync = modeled_wire_bytes(grad_bytes // 4, 4, "none",
                                  {"flat": n})["bottleneck"]

    def run_mode(overlap, depth):
        opt = optax.sgd(0.05)
        step = make_microbatched_train_step(
            loss_fn, opt, mesh, backward_passes_per_step=k,
            overlap=overlap, overlap_depth=depth, donate=False)
        from horovod_tpu.optimizer import distributed_optimizer
        dopt = distributed_optimizer(opt, axis_name="hvd",
                                     backward_passes_per_step=k,
                                     overlap=overlap, overlap_depth=depth)
        p = replicate(params, mesh)
        s = replicate(dopt.init(params), mesh)
        p, s, loss = step(p, s, batch)          # compile + warm
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            p, s, loss = step(p, s, batch)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / timed_steps
        return dt, p, float(loss)

    results = {}
    ref_params = None
    try:
        for label, overlap, depth in (("off", False, None),
                                      ("0", True, 0),
                                      ("1", True, 1),
                                      ("2", True, 2)):
            dt, p, loss = run_mode(overlap, depth)
            if not overlap:
                # legacy baseline: one sync after microbatch k — the
                # whole sync is exposed, by construction.
                exposed, frac = float(k * per_sync), 0.0
            else:
                exposed = M.OVERLAP_EXPOSED_BYTES.value(plane="microbatch")
                frac = M.OVERLAP_FRACTION.value(plane="microbatch")
            if label == "0":
                ref_params = p
            elif overlap:
                # the numerical-equivalence guarantee: scheduling only
                for key in params:
                    err = float(np.abs(np.asarray(p[key]) -
                                       np.asarray(ref_params[key])).max())
                    if err > 1e-5:
                        raise AssertionError(
                            f"depth {label}: params diverge from the "
                            f"sequential schedule by {err}")
            results[label] = {
                "step_time_s": round(dt, 6),
                "exposed_comm_bytes": int(exposed),
                "overlapped_fraction": round(float(frac), 4),
                "loss": round(loss, 6),
            }
    except AssertionError as e:
        return fail(str(e), cause="invalid-result")

    # ZeRO-1 section: monolithic flat chain vs the bucket-interleaved
    # pipeline (a small threshold forces multiple buckets on the toy).
    zthresh = dim * 4  # bytes: w1 alone spans several buckets
    zero1 = {}
    try:
        opt = optax.adamw(1e-2, weight_decay=0.01)
        zbatch = (shard_batch(jnp.asarray(xs[0]), mesh),
                  shard_batch(jnp.asarray(ys[0]), mesh))
        finals = {}
        for label, inter in (("monolithic", False), ("interleaved", True)):
            step = make_zero1_train_step(
                loss_fn, opt, mesh, interleaved=inter,
                fusion_threshold_bytes=zthresh if inter else None,
                donate=False)
            p = replicate(params, mesh)
            s = init_sharded_opt_state(
                opt, p, mesh, interleaved=inter,
                fusion_threshold_bytes=zthresh if inter else None)
            p, s, loss = step(p, s, zbatch)
            jax.block_until_ready(loss)
            t0 = time.perf_counter()
            for _ in range(timed_steps):
                p, s, loss = step(p, s, zbatch)
            jax.block_until_ready(loss)
            dt = (time.perf_counter() - t0) / timed_steps
            finals[label] = p
            row = {"step_time_s": round(dt, 6)}
            if inter:
                row["exposed_comm_bytes"] = int(
                    M.OVERLAP_EXPOSED_BYTES.value(plane="zero1"))
                row["overlapped_fraction"] = round(float(
                    M.OVERLAP_FRACTION.value(plane="zero1")), 4)
            zero1[label] = row
        for key in params:
            err = float(np.abs(np.asarray(finals["interleaved"][key]) -
                               np.asarray(finals["monolithic"][key])).max())
            if err > 1e-5:
                raise AssertionError(
                    f"interleaved zero-1 diverges from monolithic by {err}")
    except AssertionError as e:
        return fail(str(e), cause="invalid-result")

    chip = detect_chip()
    label = (f"CPU-virtual ({n} XLA host devices, loopback; no chip, no "
             "latency-hiding scheduler — exposed bytes are the "
             "analytical model, wall-clock parity expected)"
             if chip == "cpu" else chip)
    frac1 = results["1"]["overlapped_fraction"]
    print(json.dumps({
        "metric": f"overlap sweep: depth-1 microbatch pipeline hides "
                  f"{frac1:.2f} of modeled sync bytes behind compute "
                  f"(k={k}, {n} ranks) [{label}]",
        "value": frac1,
        "unit": "overlapped_fraction",
        "vs_baseline_is": "overlapped_fraction_depth1_vs_sequential",
        "vs_baseline": frac1,
        "label": label,
        "depths": results,
        "zero1": zero1,
        "equivalence_asserted": True,
        "metrics": metrics_summary(),
    }))
    return 0


def zero_bench(args) -> int:
    """ZeRO weight-update sharding sweep (parallel/zero.py;
    docs/zero.md): the chain runs at levels 1/2/3 (plus the level-0
    plain-DP baseline) on the quadratic toy with
    backward_passes_per_step=2, and at levels 1/2/3 on llama-tiny.  Per
    level the artifact records the ANALYTICAL per-rank peak
    {params, grads, opt-state, total} bytes
    (perf/costmodel.zero_memory_bytes) beside the MEASURED peak from
    the memory plane (``measured_peak_bytes`` + ``mem_drift_ratio``,
    perf/memstats.py — on the CPU-virtual harness the live-buffer
    aggregate, labeled by ``measured_source``), the modeled
    exposed_comm_bytes, the measured step_time and the ledger's
    model-drift ratio (the prediction confronted with the wall clock).  Level 1/2/3 bit-near
    parameter equivalence is asserted before anything is printed; on
    the CPU-virtual harness wall-clock parity is expected (no
    latency-hiding scheduler, loopback fabric) and the row is labeled
    accordingly — the memory columns are the headline, the step-time
    ratios the regression gate."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.parallel import zero as Z
    from horovod_tpu.parallel.data_parallel import (make_train_step,
                                                    replicate, shard_batch)
    from horovod_tpu.perf import costmodel as cm
    from horovod_tpu.perf import memstats
    from horovod_tpu.utils import metrics as M

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    mesh = hvd.mesh()
    n = hvd.size()
    k = 2
    timed_steps = 5 if args.cpu else 20
    dim = 64 if args.cpu else 1024
    thresh = dim * 4  # several buckets on the toy
    opt_slots = 2     # adamw: mu + nu

    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(dim, dim) / np.sqrt(dim),
                                jnp.float32),
              "b1": jnp.asarray(np.zeros(dim), jnp.float32),
              "w2": jnp.asarray(rng.randn(dim, 1) / np.sqrt(dim),
                                jnp.float32)}
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))

    def loss_fn(p, batch):
        x, y = batch
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)

    xs = rng.randn(k, 8 * n, dim).astype(np.float32)
    ys = rng.randn(k, 8 * n, 1).astype(np.float32)
    kbatch = (shard_batch(jnp.asarray(xs), mesh, axis=1),
              shard_batch(jnp.asarray(ys), mesh, axis=1))
    # level 0 consumes the same samples as ONE merged batch (gradient of
    # the merged mean == mean of per-microbatch gradients: same update)
    mbatch = (shard_batch(jnp.asarray(xs.reshape(-1, dim)), mesh),
              shard_batch(jnp.asarray(ys.reshape(-1, 1)), mesh))

    def run_toy_level(level):
        import horovod_tpu.perf as perf
        opt = optax.adamw(1e-2, weight_decay=0.01)
        if level == 0:
            step = make_train_step(loss_fn, opt, mesh, donate=False)
            p = replicate(params, mesh)
            s = replicate(opt.init(params), mesh)
            batch = mbatch
        else:
            step = Z.make_zero_train_step(
                loss_fn, opt, mesh, zero_level=level,
                backward_passes_per_step=k,
                fusion_threshold_bytes=thresh, params_template=params,
                donate=False)
            s = Z.init_zero_state(opt, replicate(params, mesh), mesh,
                                  zero_level=level,
                                  fusion_threshold_bytes=thresh)
            p = (Z.shard_zero3_params(replicate(params, mesh), mesh,
                                      fusion_threshold_bytes=thresh)
                 if level == 3 else replicate(params, mesh))
            batch = kbatch
        comm = cm.zero_comm_bytes(n_params, n, level, k=k)
        perf.reset()
        memstats.reset()  # per-level measured peak, not the sweep's max
        perf.configure(comm_bytes_per_step=comm["total_bytes"],
                       zero_model={"n_params": n_params, "world": n,
                                   "level": level, "k": k,
                                   "opt_slots": opt_slots})
        p, s, loss = step(p, s, batch)          # compile + warm
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            with perf.timed_step():
                p, s, loss = step(p, s, batch)
                jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / timed_steps
        rep = hvd.perf_report()
        # The measured side of the analytical peak_bytes column
        # (perf/memstats.py; docs/memory.md): live-buffer residency
        # after the level's steps, reconciled against zero_memory_bytes.
        mrow = memstats.sample(force=True) or {}
        if level == 3:
            p = Z.gather_zero3_params(p, params, mesh,
                                      fusion_threshold_bytes=thresh)
        return dt, p, float(loss), comm, rep, mrow

    toy = {}
    finals = {}
    try:
        for level in (0, 1, 2, 3):
            dt, p, loss, comm, rep, mrow = run_toy_level(level)
            finals[level] = p
            mem = cm.zero_memory_bytes(level, n_params, n,
                                       opt_slots=opt_slots)
            row = {
                "step_time_s": round(dt, 6),
                "exposed_comm_bytes": int(comm["total_bytes"]),
                "peak_bytes": mem,
                "measured_peak_bytes": mrow.get("peak_bytes_in_use"),
                "measured_source": mrow.get("source"),
                "mem_drift_ratio": mrow.get("model_drift_ratio"),
                "loss": round(loss, 6),
                "model_drift_ratio": rep.get("model_drift_ratio"),
            }
            if level >= 1:
                row["traced_exposed_comm_bytes"] = int(
                    M.OVERLAP_EXPOSED_BYTES.value(plane=f"zero{level}"))
            toy[str(level)] = row
        # the equivalence guarantee: levels 1/2/3 bit-near, level 0
        # within psum-linearity tolerance of the merged batch
        for level in (2, 3):
            for key in params:
                err = float(np.abs(np.asarray(finals[level][key]) -
                                   np.asarray(finals[1][key])).max())
                if err > 1e-5:
                    raise AssertionError(
                        f"level {level} diverges from level 1 by {err}")
        for key in params:
            err = float(np.abs(np.asarray(finals[1][key]) -
                               np.asarray(finals[0][key])).max())
            if err > 1e-4:
                raise AssertionError(
                    f"level 1 diverges from the plain-DP baseline by "
                    f"{err}")
    except AssertionError as e:
        return fail(str(e), cause="invalid-result")

    # ---- llama-tiny leg: the model-shaped workload (levels 1-3, k=1)
    from horovod_tpu.models import llama as llama_mod
    cfg = llama_mod.CONFIGS["tiny"]
    lbatch_rows, lseq, lsteps = 2 * n, 32, (2 if args.cpu else 10)
    lthresh = 32 * 1024
    lparams = llama_mod.init(jax.random.PRNGKey(0), cfg)
    ln_params = sum(int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(lparams))
    ids = np.random.RandomState(1).randint(
        0, cfg.vocab, (lbatch_rows, lseq + 1), dtype=np.int32)
    lids = shard_batch(jnp.asarray(ids), mesh)

    def run_llama_level(level):
        import horovod_tpu.perf as perf
        opt = optax.adamw(3e-4, weight_decay=0.01)
        step = Z.make_zero_train_step(
            lambda p, b: llama_mod.loss_fn(p, b, cfg),
            opt, mesh, zero_level=level, fusion_threshold_bytes=lthresh,
            params_template=lparams, donate=False)
        s = Z.init_zero_state(opt, replicate(lparams, mesh), mesh,
                              zero_level=level,
                              fusion_threshold_bytes=lthresh)
        p = (Z.shard_zero3_params(replicate(lparams, mesh), mesh,
                                  fusion_threshold_bytes=lthresh)
             if level == 3 else replicate(lparams, mesh))
        perf.reset()
        memstats.reset()
        perf.configure(zero_model={"n_params": ln_params, "world": n,
                                   "level": level,
                                   "opt_slots": opt_slots})
        p, s, loss = step(p, s, lids)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(lsteps):
            p, s, loss = step(p, s, lids)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / lsteps
        mrow = memstats.sample(force=True) or {}
        if level == 3:
            p = Z.gather_zero3_params(p, lparams, mesh,
                                      fusion_threshold_bytes=lthresh)
        return dt, p, float(loss), mrow

    llama_rows = {}
    lfinals = {}
    try:
        for level in (1, 2, 3):
            dt, p, loss, mrow = run_llama_level(level)
            lfinals[level] = p
            mem = cm.zero_memory_bytes(level, ln_params, n,
                                       opt_slots=opt_slots)
            llama_rows[str(level)] = {
                "step_time_s": round(dt, 6),
                "tokens_per_s": round(lbatch_rows * lseq / dt, 1),
                "exposed_comm_bytes": int(cm.zero_comm_bytes(
                    ln_params, n, level)["total_bytes"]),
                "peak_bytes": mem,
                "measured_peak_bytes": mrow.get("peak_bytes_in_use"),
                "measured_source": mrow.get("source"),
                "mem_drift_ratio": mrow.get("model_drift_ratio"),
                "loss": round(loss, 6),
            }
        for level in (2, 3):
            for a, b in zip(jax.tree_util.tree_leaves(lfinals[level]),
                            jax.tree_util.tree_leaves(lfinals[1])):
                err = float(np.abs(np.asarray(a) - np.asarray(b)).max())
                if err > 1e-4:
                    raise AssertionError(
                        f"llama level {level} diverges from level 1 by "
                        f"{err}")
    except AssertionError as e:
        return fail(str(e), cause="invalid-result")

    # ---- gate rows: the analytical memory reductions (deterministic)
    # and the same-run step-time ratios (correlated noise cancels)
    def _sg(level, N):
        m = cm.zero_memory_bytes(level, N, n, opt_slots=opt_slots)
        return m["grads_bytes"] + m["opt_state_bytes"]

    red2 = _sg(0, n_params) / _sg(2, n_params)
    red3p = (cm.zero_memory_bytes(0, n_params, n)["params_bytes"]
             / cm.zero_memory_bytes(3, n_params, n)["params_bytes"])
    t1 = toy["1"]["step_time_s"]
    chip = detect_chip()
    label = (f"CPU-virtual ({n} XLA host devices, loopback; no chip, no "
             "latency-hiding scheduler — memory columns are the "
             "analytical model, wall-clock parity expected)"
             if chip == "cpu" else chip)
    sub_rows = [
        {"metric": "zero level2 state+grad memory reduction",
         "value": round(red2, 3), "unit": "x", "label": label},
        {"metric": "zero level3 param memory reduction",
         "value": round(red3p, 3), "unit": "x", "label": label},
        {"metric": "zero level2 step overhead vs level1",
         "value": round(toy["2"]["step_time_s"] / t1, 4),
         "unit": "ratio", "label": label},
        {"metric": "zero level3 step overhead vs level1",
         "value": round(toy["3"]["step_time_s"] / t1, 4),
         "unit": "ratio", "label": label},
    ]
    print(json.dumps({
        "metric": f"zero sweep: level 2 cuts per-rank state+grad memory "
                  f"{red2:.1f}x, level 3 cuts params {red3p:.1f}x "
                  f"(n={n}, levels 1/2/3 bit-near asserted) [{label}]",
        "value": round(red2, 3),
        "unit": "x",
        "label": label,
        "world": n,
        "k": k,
        "toy": toy,
        "llama": llama_rows,
        "equivalence_asserted": True,
        "sub_rows": sub_rows,
        "metrics": metrics_summary(),
    }))
    return 0


def layout_bench(args) -> int:
    """3D layout sweep (parallel/layout.py + the perf/costmodel solver;
    docs/parallelism.md): solve the (dp, tp, pp) candidate table for
    llama-tiny at the live world size, then RUN every candidate mesh
    through the composed TP x PP x ZeRO chain.  Per layout the artifact
    records the MEASURED step time and peak bytes beside the solver's
    PREDICTED step decomposition and per-chip memory, the raw roofline
    drift AND a compute-calibrated drift (the dp-only row anchors the
    calibration — on the CPU-virtual harness the absolute roofline is
    fiction: 0.5 TFLOP/s "chips" on a loopback "fabric", so the
    calibrated ratio is the one the 2x gate judges), plus the ledger's
    own predicted-vs-measured ratio for the ACTIVE row
    (perf_report()["layout"], the same table doctor --perf renders).
    Cross-layout bit-near parameter equivalence is asserted before
    anything is printed — the sweep is invalid if the composition is
    not the same optimizer."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh

    import horovod_tpu as hvd
    from horovod_tpu.models import llama as llama_mod
    from horovod_tpu.parallel import layout as L
    from horovod_tpu.perf import costmodel as cm
    from horovod_tpu.perf import memstats

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    n = hvd.size()
    chip = detect_chip()
    link = "loopback" if chip == "cpu" else "ici"

    cfg = llama_mod.CONFIGS["tiny"]
    batch_rows, seq = n, 16
    n_micro = 2
    lthresh = 32 * 1024
    timed_steps = 3 if args.cpu else 10
    level = 1  # params stay replicated -> directly comparable finals

    model = cm.llama_layout_model(
        vocab=cfg.vocab, dim=cfg.dim, n_layers=cfg.n_layers,
        n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
        ffn_dim=cfg.ffn_dim, batch=batch_rows, seq=seq)
    sol = cm.solve_layout(model, n, levels=(level,), n_micro=n_micro,
                          chip=chip, link=link)
    # dp-only first: it is the equivalence reference AND the
    # calibration anchor for the relative-drift column.
    cands = sorted(sol["candidates"],
                   key=lambda r: (r["layout"]["tp"] * r["layout"]["pp"],
                                  r["rank"]))
    assert cands[0]["layout"] == {"dp": n, "tp": 1, "pp": 1}

    params = llama_mod.init(jax.random.PRNGKey(0), cfg)
    ids = np.random.RandomState(1).randint(
        0, cfg.vocab, (batch_rows, seq + 1), dtype=np.int32)
    jids = jnp.asarray(ids)

    def run_layout(dp, tp, pp):
        import horovod_tpu.perf as perf
        mesh = Mesh(np.array(jax.devices()).reshape(dp, tp, pp),
                    ("dp", "tp", "pp"))
        stacked = L.llama_layout_params(params, pp)
        opt = optax.adamw(3e-4, weight_decay=0.01)
        specs = L.llama_layout_specs(stacked)
        st = L.init_layout_state(opt, stacked, specs, mesh,
                                 zero_level=level,
                                 fusion_threshold_bytes=lthresh)
        step = L.make_llama_layout_train_step(
            cfg, opt, mesh, n_micro=n_micro, zero_level=level,
            fusion_threshold_bytes=lthresh, donate=False)
        perf.reset()
        memstats.reset()  # per-layout measured peak, not the sweep max
        perf.configure(layout_model=dict(
            model, world=n, levels=(level,), n_micro=n_micro,
            active={"dp": dp, "tp": tp, "pp": pp, "zero_level": level}))
        p, s, loss = step(stacked, st, jids)    # compile + warm
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(timed_steps):
            with perf.timed_step():
                p, s, loss = step(p, s, jids)
                jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / timed_steps
        rep = hvd.perf_report()
        mrow = memstats.sample(force=True) or {}
        return dt, p, float(loss), rep.get("layout") or {}, mrow

    def flat(p):
        # stage leaves [pp, L/pp, ...] -> [L, ...]: different-pp
        # layouts compare leaf-for-leaf
        stages = jax.tree_util.tree_map(
            lambda a: np.asarray(a).reshape((-1,) + a.shape[2:]),
            p["stages"])
        return jax.tree_util.tree_leaves(
            {"embed": p["embed"], "final_norm": p["final_norm"],
             "lm_head": p["lm_head"], "stages": stages})

    rows = {}
    finals = {}
    try:
        for cand in cands:
            lay = cand["layout"]
            key = f"{lay['dp']}x{lay['tp']}x{lay['pp']}"
            dt, p, loss, lrep, mrow = run_layout(
                lay["dp"], lay["tp"], lay["pp"])
            finals[key] = p
            pvm = lrep.get("predicted_vs_measured") or {}
            rows[key] = {
                "rank": cand["rank"],
                "zero_level": cand["zero_level"],
                "n_micro": cand["n_micro"],
                "step_time_s": round(dt, 6),
                "tokens_per_s": round(batch_rows * seq / dt, 1),
                "predicted_step_s": cand["step_s"],
                "bubble_fraction": round(cand["bubble_fraction"], 4),
                "predicted_peak_bytes": cand["memory"],
                "measured_peak_bytes": mrow.get("peak_bytes_in_use"),
                "measured_source": mrow.get("source"),
                "loss": round(loss, 6),
                "raw_drift_ratio": round(dt / cand["step_s"], 3),
                "ledger_step_ratio": pvm.get("step_ratio"),
            }
        # Calibrated drift: ONE scale factor for the whole table — the
        # geometric mean of measured/predicted — then judge each row's
        # residual.  This cancels the CPU-virtual roofline fiction and
        # leaves exactly the solver's RELATIVE story — the thing the
        # ranking runs on — confronted with the wall clock.
        base = f"{n}x1x1"
        calib = float(np.exp(np.mean([
            np.log(r["step_time_s"] / r["predicted_step_s"])
            for r in rows.values()])))
        for key, row in rows.items():
            r = row["step_time_s"] / (row["predicted_step_s"] * calib)
            row["calibrated_drift_ratio"] = round(max(r, 1.0 / r), 3)
        # The equivalence guarantee: every layout's composed chain is
        # the SAME optimizer as the dp-only chain, bit-near (float32
        # psum-ordering noise only; tests/test_layout.py proves the
        # full level matrix — the bench re-proves it on every artifact).
        ref = flat(finals[base])
        for key, p in finals.items():
            for a, b in zip(flat(p), ref):
                err = float(np.abs(a - b).max())
                if err > 1e-4:
                    raise AssertionError(
                        f"layout {key} diverges from dp-only by {err} "
                        "after the timed steps")
        chosen = sol["chosen"]["layout"]
        ckey = f"{chosen['dp']}x{chosen['tp']}x{chosen['pp']}"
        cdrift = rows[ckey]["calibrated_drift_ratio"]
        if cdrift >= 2.0:
            raise AssertionError(
                f"chosen layout {ckey} calibrated predicted-vs-measured "
                f"drift {cdrift}x >= 2x (docs/parallelism.md#cpu-virtual)")
    except AssertionError as e:
        return fail(str(e), cause="invalid-result")

    label = (f"CPU-virtual ({n} XLA host devices, loopback; no chip, no "
             "latency-hiding scheduler — the solver's RANKING and the "
             "calibrated drift are the product here, the absolute "
             "roofline is not)" if chip == "cpu" else chip)
    base_t = rows[base]["step_time_s"]
    sub_rows = [
        {"metric": "layout solver candidates (llama-tiny)",
         "value": sol["n_candidates"], "unit": "count", "label": label},
        {"metric": "layout chosen calibrated step drift",
         "value": cdrift, "unit": "x", "higher_is_better": False,
         "label": label},
    ]
    for cand in cands:
        lay = cand["layout"]
        if lay["tp"] == 1 and lay["pp"] == 1:
            continue
        key = f"{lay['dp']}x{lay['tp']}x{lay['pp']}"
        sub_rows.append(
            {"metric": f"layout {key} step overhead vs dp-only",
             "value": round(rows[key]["step_time_s"] / base_t, 4),
             "unit": "ratio", "label": label})
    print(json.dumps({
        "metric": f"layout sweep: solver ranked {sol['n_candidates']} "
                  f"(dp, tp, pp) candidates at world={n}, chose {ckey}; "
                  f"every candidate ran the composed chain bit-near the "
                  f"dp-only reference [{label}]",
        "value": cdrift,
        "unit": "x",
        "higher_is_better": False,
        "label": label,
        "world": n,
        "chip": chip,
        "link": link,
        "chosen": ckey,
        "calibration_factor": round(calib, 3),
        "layouts": rows,
        "equivalence_asserted": True,
        "sub_rows": sub_rows,
        "metrics": metrics_summary(),
    }))
    return 0


def scenario_bench(args) -> int:
    """Deterministic scenario replay (horovod_tpu/scenario;
    docs/scenarios.md): execute the spec's workload trace + fault storm
    against the real router/watch planes on a virtual clock.  Validity
    gates before an artifact prints: (1) two independent harness runs
    must produce byte-identical canonical SLO rows AND event digests
    (the determinism contract the corpus is committed under); (2) a
    third run feeds a LIVE rendezvous server's watch plane and the
    spec's ``expect_alerts`` must all appear in ``GET /alerts``
    ``fired_total`` — alert expectations are checked over the same HTTP
    surface operators read, not an in-process shortcut.  Per-scenario
    rows ride the one artifact line as ``sub_rows`` (perf/gate.py
    expands them into standalone baseline keys).  Virtual-clock
    latencies measure queueing/scheduling/recovery under the declared
    load, not chip decode — labeled accordingly."""
    from horovod_tpu.scenario import (ScenarioHarness, canonical_rows,
                                      load_scenario, rows_jsonl)
    try:
        spec = load_scenario(args.scenario)
    except (OSError, ValueError) as e:
        return fail(f"scenario spec {args.scenario!r}: {e}",
                    cause="invalid-result")
    # Knob overrides (common/knobs.py; validated at hvd.init — here the
    # same parse, tolerant of the empty-string default).
    vranks = int(os.environ.get("HOROVOD_SCENARIO_RANKS", "0") or 0) \
        or None
    tick_ms = float(os.environ.get("HOROVOD_SCENARIO_TICK_MS", "0")
                    or 0.0)
    if tick_ms > 0:
        import dataclasses as _dc
        spec = _dc.replace(spec, tick_ms=tick_ms)

    t0 = time.perf_counter()
    first = ScenarioHarness(spec, virtual_ranks=vranks).run()
    second = ScenarioHarness(spec, virtual_ranks=vranks).run()
    rows = canonical_rows(first)
    if first["digest"] != second["digest"]:
        return fail(
            f"scenario {spec.name}: event digest differs across two "
            f"runs of one seed ({first['digest'][:12]} vs "
            f"{second['digest'][:12]}) — the trace generator is "
            "nondeterministic", cause="invalid-result")
    if rows_jsonl(rows) != rows_jsonl(canonical_rows(second)):
        return fail(
            f"scenario {spec.name}: SLO rows differ across two runs of "
            "one seed — the replay harness is nondeterministic",
            cause="invalid-result")

    # Live-server leg: the watch plane under a real RendezvousServer,
    # alerts read back over HTTP like an operator would.
    from horovod_tpu.runner.http_server import RendezvousServer
    server = RendezvousServer(port=0)
    port = server.start()
    try:
        if spec.alert_rules:
            from horovod_tpu.watch import parse_rules
            server.install_alert_rules(parse_rules(spec.alert_rules))
        live = ScenarioHarness(spec, watch=server.watch_state,
                               virtual_ranks=vranks).run()
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/alerts", timeout=30) as resp:
            alerts_view = json.loads(resp.read().decode())
    finally:
        server.stop()
    wall = time.perf_counter() - t0
    if rows_jsonl(canonical_rows(live)) != rows_jsonl(rows):
        return fail(
            f"scenario {spec.name}: SLO rows differ between the "
            "private and live watch sinks — the watch feed leaked "
            "into the replay", cause="invalid-result")
    fired = sorted({f["rule"]
                    for f in alerts_view.get("fired_total", [])
                    if f.get("count", 0) > 0})
    missing = [r for r in spec.expect_alerts if r not in fired]
    if missing:
        return fail(
            f"scenario {spec.name}: expect_alerts never fired: "
            f"{missing} (GET /alerts fired_total: {fired})",
            cause="invalid-result")

    slo = first["slo"]
    req = first["requests"]
    label = ("CPU-virtual clock (tick arithmetic — queueing/"
             "scheduling/recovery under the declared load, not chip "
             "decode)")
    print(json.dumps({
        "sub_rows": rows,
        "metric": f"scenario {spec.name} replay "
                  f"({req['completed']}/{req['arrived']} reqs, "
                  f"{first['virtual_ranks']} vranks, "
                  f"{first['restarts']} restart(s), ttft p99 "
                  f"{slo['ttft_p99_s'] * 1e3:.1f} ms) [{label}]",
        "value": slo["throughput_tok_s"],
        "unit": "tokens/sec",
        "vs_baseline_is": "completed_over_arrived",
        "vs_baseline": round(req["completed"] / max(1, req["arrived"]),
                             4),
        "label": label,
        "wall_s": round(wall, 3),
        "scenario": os.path.basename(args.scenario),
        "digest": first["digest"],
        "slo": slo,
        "requests": req,
        "per_rank": first["per_rank"],
        "phases": first["phases"],
        "storms": first["storms"],
        "restarts": first["restarts"],
        "alerts": {"fired": fired,
                   "expected": list(spec.expect_alerts),
                   "missing": missing, "ok": not missing},
        "metrics": metrics_summary(),
    }))
    return 0


def serve_bench(args) -> int:
    """Serving load-generator sweep (serve/engine.py; docs/serving.md):
    the continuous-batching engine under two canonical load shapes —
    CLOSED-LOOP (a fixed pool of concurrent users, each resubmitting on
    completion: the throughput ceiling) and POISSON arrivals (open-loop
    at ~60%% of the measured closed-loop request rate: the latency-
    under-load view).  Per mode the artifact records {throughput_tok_s,
    ttft_p50/p99, tpot_p50/p99, batch_fill}; on the CPU-virtual harness
    the absolute numbers measure the host scheduler + XLA-CPU decode,
    not chip serving — the mode exists to prove the machinery and give
    the trajectory, and is labeled accordingly."""
    import jax

    import horovod_tpu as hvd
    from horovod_tpu.models import llama
    from horovod_tpu.serve.config import ServeConfig
    from horovod_tpu.serve.engine import ServeEngine

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    if args.cpu:
        cfg = llama.CONFIGS["tiny"]
        prompt_len, max_new, total, users = 12, 8, 16, 4
        scfg = ServeConfig(max_slots=4, block_size=4, cache_blocks=64,
                           max_seq_len=64, max_batch_tokens=32,
                           prefill_chunk=16)
    else:
        cfg = llama.CONFIGS[args.model if args.model != "bench"
                            else "mini"]
        prompt_len, max_new, total, users = 128, 64, 64, 8
        scfg = ServeConfig(max_slots=16, block_size=16,
                           cache_blocks=1024,
                           max_seq_len=min(1024, cfg.max_seq),
                           max_batch_tokens=512, prefill_chunk=128)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(llama, cfg, params, scfg, mesh=hvd.mesh())
    rng = np.random.RandomState(0)

    def new_prompt():
        # +/-25% length jitter so slots genuinely desynchronize
        n = max(2, int(prompt_len * (0.75 + 0.5 * rng.rand())))
        return rng.randint(0, cfg.vocab, n).tolist()

    def drain(arrival_times):
        """Run the engine against an arrival schedule (None = closed
        loop: resubmit on completion).  Returns the mode's SLO row."""
        t0 = time.perf_counter()
        tok0 = engine._tokens_prefill + engine._tokens_decode
        submitted = 0
        done = []
        fills = []

        def submit_one():
            nonlocal submitted
            engine.submit(new_prompt(), max_new,
                          req_id=f"lg-{submitted}")
            submitted += 1

        if arrival_times is None:
            for _ in range(min(users, total)):
                submit_one()
        while len(done) < total:
            now = time.perf_counter() - t0
            if arrival_times is not None:
                while submitted < total and \
                        arrival_times[submitted] <= now:
                    submit_one()
                if not engine.has_work() and submitted < total:
                    time.sleep(min(0.005,
                                   arrival_times[submitted] - now))
            rep = engine.step()
            if rep["processed"]:
                fills.append(rep["processed"] / scfg.max_batch_tokens)
            for req in rep["finished"]:
                done.append(req)
                if arrival_times is None and submitted < total:
                    submit_one()
        wall = time.perf_counter() - t0
        tokens = engine._tokens_prefill + engine._tokens_decode - tok0
        ttfts = [r.ttft() for r in done]
        tpots = [r.tpot() for r in done if r.tpot() is not None]
        # Per-component TTFT breakdown through serve/trace.py
        # ``attribute`` — the request-lifecycle components, summing
        # exactly to each request's TTFT.  Engine-direct (no router),
        # so placement/handoff/stream are structurally zero and the
        # queue and prefill legs carry the whole story.
        from horovod_tpu.serve import trace as serve_trace
        comp_vals = {c: [] for c in serve_trace.COMPONENTS}
        for r in done:
            measured = {}
            if r.admitted_t is not None:
                measured["queue"] = r.admitted_t - r.submitted_t
                if r.first_token_t is not None:
                    measured["prefill"] = \
                        r.first_token_t - r.admitted_t
            comps, _ = serve_trace.attribute(r.ttft() or 0.0, measured)
            for c, v in comps.items():
                comp_vals[c].append(v)
        breakdown = {c: round(float(np.percentile(vs, 50)), 5)
                     for c, vs in comp_vals.items() if vs}
        return {
            "ttft_breakdown": breakdown,
            "requests": len(done),
            "wall_s": round(wall, 4),
            "throughput_tok_s": round(tokens / wall, 2),
            "requests_per_s": round(len(done) / wall, 3),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 5),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 5),
            "tpot_p50_s": round(float(np.percentile(tpots, 50)), 5),
            "tpot_p99_s": round(float(np.percentile(tpots, 99)), 5),
            "batch_fill": round(float(np.mean(fills)), 4),
        }

    closed = drain(None)
    # Open-loop Poisson at ~60% of the measured closed-loop request
    # rate: under the saturation knee, so the row shows latency, not
    # queue blow-up.  The schedule comes from the scenario trace
    # machinery's named built-in (scenario/trace.py BUILTIN_TRACES) so
    # --serve and --scenario draw arrivals from ONE seeded generator.
    from horovod_tpu.scenario import builtin_arrivals
    arrivals = builtin_arrivals("serve-bench-poisson",
                                closed_loop_rps=closed["requests_per_s"],
                                n=total)
    poisson = drain(arrivals)

    for mode, row in (("closed_loop", closed), ("poisson", poisson)):
        if row["requests"] != total or row["ttft_p50_s"] <= 0 or \
                row["tpot_p50_s"] <= 0:
            return fail(f"serve {mode} row implausible: {row}",
                        cause="invalid-result")
    chip = detect_chip()
    label = (f"CPU-virtual ({hvd.size()} XLA host devices; no chip — "
             "latencies measure the host scheduler + XLA-CPU decode, "
             "not chip serving)" if chip == "cpu" else chip)

    legs = serve_speed_legs(llama, cfg, params, hvd.mesh(), label)
    if isinstance(legs, int):
        return legs  # a leg failed its byte-identity contract
    # Gate-able per-leg rows ride the ONE artifact line as sub_rows (the
    # bench supervisor forwards only the last stdout line);
    # perf/gate.py load_artifacts expands them into standalone rows.
    sub_rows = legs.pop("gate_rows")
    # Per-component TTFT breakdown rides the same artifact as gate-able
    # sub_rows: the gate watches the queue and prefill legs of the
    # closed-loop TTFT independently (a scheduler regression can hide
    # in one leg while the blended p50 stays flat).
    for comp in ("queue", "prefill"):
        sub_rows.append({
            "metric": f"serve closed-loop ttft {comp} p50",
            "value": round(
                closed["ttft_breakdown"].get(comp, 0.0) * 1e3, 3),
            "unit": "ms",
            "higher_is_better": False,
            "label": label})

    print(json.dumps({
        "sub_rows": sub_rows,
        "metric": f"serve load-gen closed-loop throughput "
                  f"({closed['throughput_tok_s']:.0f} tok/s at batch "
                  f"fill {closed['batch_fill']:.2f}, Poisson ttft p99 "
                  f"{poisson['ttft_p99_s'] * 1e3:.1f} ms, "
                  f"{total} reqs, prompt~{prompt_len}, gen {max_new}) "
                  f"[{label}]",
        "value": closed["throughput_tok_s"],
        "unit": "tokens/sec",
        "vs_baseline_is": "closed_loop_batch_fill",
        "vs_baseline": closed["batch_fill"],
        "label": label,
        "closed_loop": closed,
        "poisson": poisson,
        "serve_config": {"max_slots": scfg.max_slots,
                         "block_size": scfg.block_size,
                         "cache_blocks": scfg.cache_blocks,
                         "max_batch_tokens": scfg.max_batch_tokens,
                         "prefill_chunk": scfg.prefill_chunk},
        "legs": legs,
        "metrics": metrics_summary(),
    }))
    return 0


def serve_users_bench(args) -> int:
    """Control-plane saturation sweep (docs/control-plane.md): a
    closed-loop user-count sweep through the REAL front door — POST
    /generate on the rendezvous server, KV enqueue, FleetFrontend
    drain/publish, ndjson stream back — with a scripted fixed-cost
    engine (1 ms/tick, one token per request per tick) so the knee the
    sweep locates is the ROUTER+KV's, not the model's.  Run twice:

      * ``single`` — 1 KV shard, direct streaming OFF (every token a
        serve_out KV PUT polled by the router: the pre-scale-out path);
      * ``sharded_direct`` — ``--kv-shards 3`` + the persistent direct
        token stream (the scale-out control plane).

    Knee = smallest user count whose throughput reaches 90%% of the
    config's max.  The artifact gates the per-config knee throughput
    and the scaled/baseline ratio via PERF_BASELINE.json sub_rows.
    CPU-virtual: loopback HTTP in one process — absolute numbers
    measure the host's scheduler + GIL, the COMPARISON is the claim."""
    import threading
    import urllib.request

    from horovod_tpu.runner import http_client as hc
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.serve.router import RouterState
    from horovod_tpu.serve.worker import FleetFrontend

    user_counts = [int(x) for x in str(args.users).split(",")]
    tick_s = 0.001
    max_new = 16
    warmup_s, window_s = 0.4, 1.5

    class TickEngine:
        """FleetFrontend-contract engine with a fixed 1 ms tick: one
        token per active request per step, deterministic content."""

        def __init__(self):
            self.tick = 0
            self.active = {}
            self.completed = 0

        def submit(self, tokens, max_new_tokens, req_id=None,
                   eos_id=None):
            base = sum(int(t) for t in tokens)
            self.active[req_id] = [(base + i) % 1000
                                   for i in range(max_new_tokens)]

        def has_work(self):
            return bool(self.active)

        def step(self):
            time.sleep(tick_s)  # the modeled decode tick
            emitted, finished = {}, []
            for rid in sorted(self.active):
                emitted[rid] = [self.active[rid].pop(0)]
                if not self.active[rid]:
                    del self.active[rid]
                    finished.append(_UserDone(rid))
                    self.completed += 1
            if emitted:
                self.tick += 1
            return {"tick": self.tick, "processed": len(emitted),
                    "emitted": emitted, "finished": finished}

        def stats(self):
            return {"tick": self.tick, "completed": self.completed,
                    "active": len(self.active)}

    class _UserDone:
        def __init__(self, rid):
            self.req_id = rid
            self.finish_reason = "completed"

        def ttft(self):
            return tick_s

        def tpot(self):
            return tick_s

    def run_config(shards, direct):
        server = RendezvousServer(host="127.0.0.1", shards=shards)
        port = server.start()
        addrs = [("127.0.0.1", p) for p in server.shard_ports]
        if shards > 1:
            hc.install_shard_map(addrs)
        # No shedding: saturation must hit the transport, not admission.
        server._httpd.serve_router = RouterState(
            max_pending=1 << 20, shed_high=1 << 20, journal=True)
        frontend = FleetFrontend(TickEngine(), "127.0.0.1", port, 0, 1,
                                 direct=direct)
        ft = threading.Thread(target=frontend.run, daemon=True)
        ft.start()
        done = {"requests": 0, "tokens": 0}
        done_lock = threading.Lock()
        counting = threading.Event()
        stop = threading.Event()

        def user_loop(uid):
            body = json.dumps({"tokens": [uid + 1, uid + 2],
                               "max_new_tokens": max_new}).encode()
            while not stop.is_set():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate", data=body,
                    method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=60) as r:
                        lines = r.read().splitlines()
                except OSError:
                    continue
                rec = json.loads(lines[-1]) if lines else {}
                if rec.get("done") and counting.is_set():
                    with done_lock:
                        done["requests"] += 1
                        done["tokens"] += len(rec.get("tokens") or ())

        rows = []
        try:
            for n in user_counts:
                stop.clear()
                counting.clear()
                users = [threading.Thread(target=user_loop, args=(u,),
                                          daemon=True)
                         for u in range(n)]
                for u in users:
                    u.start()
                time.sleep(warmup_s)
                with done_lock:
                    done["requests"] = done["tokens"] = 0
                counting.set()
                time.sleep(window_s)
                counting.clear()
                with done_lock:
                    reqs, toks = done["requests"], done["tokens"]
                stop.set()
                for u in users:
                    u.join(timeout=90)
                rows.append({"users": n,
                             "requests_per_s": round(reqs / window_s, 2),
                             "tok_s": round(toks / window_s, 1)})
        finally:
            # graceful exit: the drain signal stops the frontend loop
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/admin/drain", data=b"{}",
                    method="POST"), timeout=30).read()
            except OSError:
                pass
            ft.join(timeout=30)
            if shards > 1:
                hc.install_shard_map(None)
            server.stop()
        peak = max(r["tok_s"] for r in rows)
        knee = next((r for r in rows if r["tok_s"] >= 0.9 * peak),
                    rows[-1])
        return {"rows": rows, "peak_tok_s": peak,
                "knee_users": knee["users"],
                "knee_tok_s": knee["tok_s"]}

    single = run_config(shards=1, direct=False)
    scaled = run_config(shards=3, direct=True)
    for tag, res in (("single", single), ("sharded_direct", scaled)):
        if res["peak_tok_s"] <= 0:
            return fail(f"serve --users {tag} sweep moved no tokens: "
                        f"{res}", cause="invalid-result")
    gain = scaled["knee_tok_s"] / max(single["knee_tok_s"], 1e-9)
    label = ("CPU-virtual control plane (loopback HTTP, scripted 1 ms "
             "engine tick — measures router+KV, not decode)")
    sub_rows = [
        {"metric": "serve ctrl-plane single knee throughput "
                   f"(knee at {single['knee_users']} users)",
         "value": single["knee_tok_s"], "unit": "tokens/sec",
         "higher_is_better": True, "label": label},
        {"metric": "serve ctrl-plane sharded-direct knee throughput "
                   f"(knee at {scaled['knee_users']} users)",
         "value": scaled["knee_tok_s"], "unit": "tokens/sec",
         "higher_is_better": True, "label": label},
        {"metric": "serve ctrl-plane scale-out gain "
                   "(sharded+direct vs single, knee tok/s)",
         "value": round(gain, 3), "unit": "x",
         "higher_is_better": True, "label": label},
    ]
    print(json.dumps({
        "sub_rows": sub_rows,
        "metric": "serve ctrl-plane saturation sweep "
                  f"(single knee {single['knee_tok_s']:.0f} tok/s at "
                  f"{single['knee_users']} users; sharded+direct "
                  f"{scaled['knee_tok_s']:.0f} tok/s at "
                  f"{scaled['knee_users']} users; gain {gain:.2f}x) "
                  f"[{label}]",
        "value": scaled["knee_tok_s"], "unit": "tokens/sec",
        "vs_baseline_is": "single_knee_tok_s",
        "vs_baseline": single["knee_tok_s"],
        "label": label,
        "user_counts": user_counts,
        "tick_ms": tick_s * 1e3, "max_new_tokens": max_new,
        "window_s": window_s,
        "single": single, "sharded_direct": scaled,
    }))
    return 0


def serve_replicas_bench(args) -> int:
    """Replica scale-out sweep (docs/serving.md#replicated-tier): the
    ``--users`` saturation harness repeated against N independent
    replica fleets — each a FleetFrontend + slot-capped scripted tick
    engine — registered behind ONE router process with prefix-affinity
    routing.
    The workload is grouped shared-prefix traffic (each closed-loop
    user belongs to one of a few hot prefix groups), so the sweep
    measures the two replicated-tier claims at once:

      * the saturation knee scales with the replica count (the single
        lockstep fleet was the ceiling the tier removes);
      * affinity routing pins each prefix group to one replica — hit
        rate measured from the ``X-Serve-Affinity-Blocks`` response
        header — where the least-loaded-only baseline (affinity knob
        off) scatters it (hit rate 0 by construction).

    CPU-virtual: every replica is a thread in this process, so the
    scale-out gain measures overlap of control-plane waits (loopback
    HTTP, KV locks, the 1 ms engine sleep) under the GIL — the
    COMPARISON across replica counts is the claim, not the absolute
    tok/s.  Artifact gates per-replica-count knee throughput, the
    1->2 scale-out gain, and the affinity hit rate via
    PERF_BASELINE.json sub_rows."""
    import threading
    import urllib.request

    import horovod_tpu.serve.worker as worker_mod
    from horovod_tpu.runner import http_client as hc
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.serve.replica import (ReplicaRouter, fold_digest,
                                           prompt_fingerprints)
    from horovod_tpu.serve.router import RouterState
    from horovod_tpu.serve.worker import FleetFrontend

    replica_counts = sorted({int(x)
                             for x in str(args.replicas).split(",")})
    user_counts = [int(x) for x in str(args.users).split(",")]
    tick_s = 0.025
    slots = 2           # modeled decode slots per replica fleet
    chunk = 8           # tokens emitted per scheduled request per tick
    block = 4           # fingerprint block size (registered with router)
    n_groups = 8        # hot shared-prefix groups (lcm-friendly for 1/2/4)
    prefix_blocks = 3   # full blocks of shared prefix per group
    max_new = 32
    warmup_s, window_s = 0.5, 1.5

    # Deterministic per-group shared prefixes: 3 full blocks each, so
    # the router sees 3 matchable fingerprints per prompt.
    prefixes = [[(17 * g + 3 * i + 1) % 251 for i in range(
        block * prefix_blocks)] for g in range(n_groups)]

    class TickEngine:
        """Scripted slot-capped engine: each 25 ms tick (a GIL-released
        sleep — the modeled decode fleet) serves the first ``slots``
        queued requests FCFS, emitting a ``chunk``-token part each, so
        ONE replica's ceiling is slots*chunk/tick = 640 tok/s by
        construction — far below the router process's own CPU cap and the sweep observes the tier scale until the
        shared router process saturates.  The replica affinity contract
        rides on top: submitted prompts' rolling block fingerprints
        accumulate as the advertised 'radix tree', and stats carry the
        queue depth the least-loaded fallback reads."""

        def __init__(self):
            self.tick = 0
            self.active = {}
            self.order = []  # FCFS arrival order
            self.completed = 0
            self._fps = set()

        def submit(self, tokens, max_new_tokens, req_id=None,
                   eos_id=None):
            base = sum(int(t) for t in tokens)
            self.active[req_id] = [(base + i) % 1000
                                   for i in range(max_new_tokens)]
            self.order.append(req_id)
            self._fps.update(prompt_fingerprints(tokens, block))

        def prefix_fps(self):
            fps = sorted(self._fps)[:64]
            return fps, fold_digest(fps)

        def has_work(self):
            return bool(self.active)

        def step(self):
            time.sleep(tick_s)  # the modeled decode tick
            emitted, finished = {}, []
            for rid in self.order[:slots]:
                emitted[rid] = self.active[rid][:chunk]
                del self.active[rid][:chunk]
                if not self.active[rid]:
                    del self.active[rid]
                    finished.append(_ReplicaDone(rid))
                    self.completed += 1
            self.order = [r for r in self.order if r in self.active]
            if emitted:
                self.tick += 1
            return {"tick": self.tick, "processed": len(emitted),
                    "emitted": emitted, "finished": finished}

        def stats(self):
            return {"tick": self.tick, "completed": self.completed,
                    "active": len(self.active),
                    "waiting": len(self.active)}

    class _ReplicaDone:
        def __init__(self, rid):
            self.req_id = rid
            self.finish_reason = "completed"

        def ttft(self):
            return tick_s

        def tpot(self):
            return tick_s

    def run_config(n_replicas, affinity):
        """One (replica count, affinity) config: fresh server, N
        registered replica fleets, the full user-count sweep.  Returns
        the per-user-count rows, the knee, and the measured affinity
        hit rate over every counted request."""
        server = RendezvousServer(host="127.0.0.1", shards=3)
        port = server.start()
        hc.install_shard_map([("127.0.0.1", p)
                              for p in server.shard_ports])
        # No shedding (saturation must hit the transport, not
        # admission), and an explicit affinity switch per config.
        server._httpd.serve_routers = {
            k: RouterState(max_pending=1 << 20, shed_high=1 << 20,
                           journal=True) for k in range(n_replicas)}
        server._httpd.serve_router = server._httpd.serve_routers[0]
        server._httpd.serve_replicas = ReplicaRouter(
            block_size=block, affinity=affinity, dead_after_s=30.0)
        frontends = [FleetFrontend(TickEngine(), "127.0.0.1", port, 0, 1,
                                   direct=True, replica_id=k)
                     for k in range(n_replicas)]
        for fe in frontends:
            fe.register_replica({"replicas": n_replicas,
                                 "block_size": block})
            fe._publish_stats(force=True)
        threads = [threading.Thread(target=fe.run, daemon=True)
                   for fe in frontends]
        for t in threads:
            t.start()

        done = {"requests": 0, "tokens": 0, "hits": 0, "routed": 0}
        done_lock = threading.Lock()
        counting = threading.Event()
        stop = threading.Event()

        def user_loop(uid):
            toks = prefixes[uid % n_groups] + [uid + 1, uid + 2]
            body = json.dumps({"tokens": toks,
                               "max_new_tokens": max_new}).encode()
            while not stop.is_set():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate", data=body,
                    method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=60) as r:
                        hit = int(r.headers.get(
                            "X-Serve-Affinity-Blocks", 0) or 0)
                        lines = r.read().splitlines()
                except (OSError, ValueError):
                    continue
                rec = json.loads(lines[-1]) if lines else {}
                if rec.get("done") and counting.is_set():
                    with done_lock:
                        done["requests"] += 1
                        done["tokens"] += len(rec.get("tokens") or ())
                        done["routed"] += 1
                        done["hits"] += 1 if hit > 0 else 0

        rows = []
        try:
            for n in user_counts:
                stop.clear()
                counting.clear()
                users = [threading.Thread(target=user_loop, args=(u,),
                                          daemon=True)
                         for u in range(n)]
                for u in users:
                    u.start()
                time.sleep(warmup_s)
                with done_lock:
                    done["requests"] = done["tokens"] = 0
                counting.set()
                time.sleep(window_s)
                counting.clear()
                with done_lock:
                    reqs, toks = done["requests"], done["tokens"]
                stop.set()
                for u in users:
                    u.join(timeout=90)
                rows.append({"users": n,
                             "requests_per_s": round(reqs / window_s, 2),
                             "tok_s": round(toks / window_s, 1)})
        finally:
            # graceful exit: ONE drain fans out to every replica fleet
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/admin/drain", data=b"{}",
                    method="POST"), timeout=30).read()
            except OSError:
                pass
            for t in threads:
                t.join(timeout=30)
            hc.install_shard_map(None)
            server.stop()
        peak = max(r["tok_s"] for r in rows)
        knee = next((r for r in rows if r["tok_s"] >= 0.9 * peak),
                    rows[-1])
        with done_lock:
            routed, hits = done["routed"], done["hits"]
        return {"replicas": n_replicas, "affinity": affinity,
                "rows": rows, "peak_tok_s": peak,
                "knee_users": knee["users"], "knee_tok_s": knee["tok_s"],
                "affinity_hit_rate": round(hits / max(routed, 1), 4),
                "routed": routed}

    # Fleet stats must beat the router's load/affinity staleness at
    # bench time scales: 1 Hz heartbeats against 1.5 s windows would
    # measure the heartbeat, not the tier.
    old_interval = worker_mod._STATS_INTERVAL_S
    worker_mod._STATS_INTERVAL_S = 0.05
    try:
        results = {n: run_config(n, affinity=True)
                   for n in replica_counts}
        # The hit-rate control: the biggest tier again with the
        # affinity knob off — pure least-loaded placement scatters the
        # prefix groups (hit rate 0 by construction; the row documents
        # the comparison, the gate rides the affinity-on rate).
        control = run_config(max(replica_counts), affinity=False)
    finally:
        worker_mod._STATS_INTERVAL_S = old_interval

    for n, res in results.items():
        if res["peak_tok_s"] <= 0:
            return fail(f"serve --replicas {n} sweep moved no tokens: "
                        f"{res}", cause="invalid-result")
    label = ("CPU-virtual replica tier (loopback HTTP, slot-capped "
             "scripted engine ticks, N replica threads in one process "
             "— measures router+KV overlap, not decode)")
    sub_rows = []
    for n in replica_counts:
        res = results[n]
        sub_rows.append(
            {"metric": f"serve replica tier knee throughput r{n} "
                       f"(knee at {res['knee_users']} users)",
             "value": res["knee_tok_s"], "unit": "tokens/sec",
             "higher_is_better": True, "label": label})
    gain2 = None
    if 1 in results and 2 in results:
        gain2 = results[2]["knee_tok_s"] / max(
            results[1]["knee_tok_s"], 1e-9)
        sub_rows.append(
            {"metric": "serve replica scale-out gain 1to2 "
                       "(knee tok/s, 2 vs 1 replicas)",
             "value": round(gain2, 3), "unit": "x",
             "higher_is_better": True, "label": label})
    top = max(replica_counts)
    if 1 in results and top > 2:
        sub_rows.append(
            {"metric": f"serve replica scale-out gain 1to{top} "
                       f"(knee tok/s, {top} vs 1 replicas)",
             "value": round(results[top]["knee_tok_s"] / max(
                 results[1]["knee_tok_s"], 1e-9), 3),
             "unit": "x", "higher_is_better": True, "label": label})
    sub_rows.append(
        {"metric": f"serve replica affinity hit rate r{top} "
                   f"({n_groups} prefix groups; least-loaded control "
                   f"{control['affinity_hit_rate']:.2f})",
         "value": results[top]["affinity_hit_rate"], "unit": "ratio",
         "higher_is_better": True, "label": label})
    gain_txt = f"{gain2:.2f}x" if gain2 is not None else "n/a"
    print(json.dumps({
        "sub_rows": sub_rows,
        "metric": "serve replica scale-out sweep "
                  f"(knees {[results[n]['knee_tok_s'] for n in replica_counts]} "
                  f"tok/s at replicas {replica_counts}; 1->2 gain "
                  f"{gain_txt}; affinity hit rate "
                  f"{results[top]['affinity_hit_rate']:.2f} vs control "
                  f"{control['affinity_hit_rate']:.2f}) [{label}]",
        "value": results[top]["knee_tok_s"], "unit": "tokens/sec",
        "label": label,
        "replica_counts": replica_counts, "user_counts": user_counts,
        "tick_ms": tick_s * 1e3, "max_new_tokens": max_new,
        "window_s": window_s, "prefix_groups": n_groups,
        "results": {str(n): results[n] for n in replica_counts},
        "least_loaded_control": control,
    }))
    return 0


def serve_speed_legs(model, cfg, params, mesh, label):
    """The raw-speed acceptance experiments (docs/serving.md#raw-speed),
    each leg independently toggled off vs on over the SAME deterministic
    workload with byte-identity asserted between the runs:

      * prefix — shared-prefix traffic; TTFT p50 drops because repeated
        prefills become radix-cache hits;
      * chunked — one long prompt landing amid short decode streams;
        the victims' worst inter-token gap stays bounded because the
        prompt is split across ticks (and the verify row stays narrow);
      * spec — n-gram-friendly decode; tok/s rises because accepted
        drafts emit several verified tokens per tick.

    Returns {leg: row, "gate_rows": [...]} or fail()'s rc on a broken
    identity contract.  CPU-virtual caveats apply (the caller labels)."""
    from horovod_tpu.serve.config import ServeConfig
    from horovod_tpu.serve.engine import ServeEngine

    def run(scfg, reqs, warm=()):
        """Fresh engine; warm requests complete first — they absorb the
        jit compile (and prime the prefix cache where one is on) so the
        measured wall is serving, not XLA compilation.  Then ``reqs``
        run closed-loop.  Returns (per-request Request objects, wall
        seconds, max inter-token gap seconds per request, engine)."""
        engine = ServeEngine(model, cfg, params, scfg, mesh=mesh)
        for rid, toks, n in list(warm) + [("leg-warmup", [1, 2, 3], 2)]:
            engine.submit(toks, n, req_id=rid)
        engine.flush()
        handles = [engine.submit(toks, n, req_id=rid)
                   for rid, toks, n in reqs]
        gaps = {rid: 0.0 for rid, _, _ in reqs}
        last = {}
        t0 = time.perf_counter()
        while engine.has_work():
            rep = engine.step()
            now = time.perf_counter()
            for rid in rep["emitted"]:
                if rid in gaps:
                    if rid in last:
                        gaps[rid] = max(gaps[rid], now - last[rid])
                    last[rid] = now
        wall = time.perf_counter() - t0
        return handles, wall, gaps, engine

    def identity(tag, off_handles, on_handles):
        for a, b in zip(off_handles, on_handles):
            if a.out_tokens != b.out_tokens:
                return fail(
                    f"serve {tag} leg broke greedy byte-identity: "
                    f"{a.req_id} {a.out_tokens} != {b.out_tokens}",
                    cause="invalid-result")
        return None

    def p50(values):
        return float(np.percentile(values, 50))

    base = dict(max_slots=4, block_size=4, cache_blocks=256,
                max_seq_len=min(128, cfg.max_seq), max_batch_tokens=32,
                prefill_chunk=16)
    rng = np.random.RandomState(42)
    legs = {}
    gate_rows = []

    # --- leg 1: radix prefix cache on shared-prefix traffic ----------
    prefix_toks = rng.randint(0, cfg.vocab, 112).tolist()
    shared = [(f"px-{i}",
               prefix_toks + rng.randint(0, cfg.vocab, 8).tolist(), 8)
              for i in range(8)]
    warm = [("px-warm", prefix_toks + [1, 2, 3], 4)]
    rows = {}
    for mode, on in (("off", False), ("on", True)):
        scfg = ServeConfig(prefix_cache=on, spec_decode=False, **base)
        handles, wall, _, engine = run(scfg, shared, warm=warm)
        st = engine.stats()
        rows[mode] = {
            "ttft_p50_s": round(p50([r.ttft() for r in handles]), 5),
            "wall_s": round(wall, 4),
            "prefill_chunks": st["prefill_chunks"],
            "prefix_hit_rate": st["prefix_cache"].get("hit_rate"),
            "blocks_shared": st["prefix_cache"].get("blocks_shared"),
            "handles": handles,
        }
    rc = identity("prefix", rows["off"]["handles"], rows["on"]["handles"])
    if rc is not None:
        return rc
    speedup = rows["off"]["ttft_p50_s"] / max(rows["on"]["ttft_p50_s"],
                                              1e-9)
    legs["prefix"] = {m: {k: v for k, v in r.items() if k != "handles"}
                      for m, r in rows.items()}
    legs["prefix"]["ttft_p50_speedup"] = round(speedup, 2)
    legs["prefix"]["byte_identical"] = True
    gate_rows.append({
        "metric": "serve prefix ttft p50 speedup (shared-prefix "
                  "workload, off->on)",
        "value": round(speedup, 3), "unit": "x",
        "higher_is_better": True, "label": label})

    # --- leg 2: chunked prefill vs one-shot under interference -------
    victims = [(f"v-{i}", rng.randint(0, cfg.vocab, 8).tolist(), 24)
               for i in range(2)]
    intruder = [("long", rng.randint(0, cfg.vocab, 120).tolist(), 4)]
    rows = {}
    for mode, chunk in (("unchunked", 128), ("chunked", 16)):
        scfg = ServeConfig(prefix_cache=False, spec_decode=False,
                           **dict(base, prefill_chunk=chunk,
                                  max_batch_tokens=160))
        handles, wall, gaps, _ = run(scfg, victims + intruder)
        rows[mode] = {
            "victim_max_gap_s": round(max(gaps[rid]
                                          for rid, _, _ in victims), 5),
            "victim_tpot_p99_s": round(float(np.percentile(
                [h.tpot() for h in handles[:len(victims)]], 99)), 5),
            "wall_s": round(wall, 4),
            "prefill_chunk": chunk,
            "handles": handles,
        }
    rc = identity("chunked", rows["unchunked"]["handles"],
                  rows["chunked"]["handles"])
    if rc is not None:
        return rc
    bound = rows["unchunked"]["victim_max_gap_s"] / \
        max(rows["chunked"]["victim_max_gap_s"], 1e-9)
    legs["chunked"] = {m: {k: v for k, v in r.items() if k != "handles"}
                       for m, r in rows.items()}
    legs["chunked"]["gap_bound_ratio"] = round(bound, 2)
    legs["chunked"]["byte_identical"] = True
    gate_rows.append({
        "metric": "serve chunked prefill interference bound "
                  "(victim max-gap, unchunked/chunked)",
        "value": round(bound, 3), "unit": "x",
        "higher_is_better": True, "label": label})

    # --- leg 3: speculative decoding on n-gram-friendly decode -------
    # Cyclic prompts: a random-init greedy trajectory falls into short
    # cycles, exactly what prompt-lookup drafts (and what production
    # extraction/quote-heavy traffic looks like).
    cyc = [(f"sp-{i}",
            (rng.randint(0, cfg.vocab, 3).tolist() * 8)[:24], 24)
           for i in range(4)]
    rows = {}
    for mode, on in (("off", False), ("on", True)):
        scfg = ServeConfig(prefix_cache=False, spec_decode=on,
                           spec_k=4, **base)
        handles, wall, _, engine = run(scfg, cyc)
        st = engine.stats()
        decode_toks = sum(len(h.out_tokens) for h in handles)
        rows[mode] = {
            "decode_tok_s": round(decode_toks / wall, 2),
            "wall_s": round(wall, 4),
            "spec_accept_rate": st["spec"].get("accept_rate"),
            "drafted": st["spec"].get("drafted_tokens"),
            "accepted": st["spec"].get("accepted_tokens"),
            "handles": handles,
        }
    rc = identity("spec", rows["off"]["handles"], rows["on"]["handles"])
    if rc is not None:
        return rc
    speedup = rows["on"]["decode_tok_s"] / \
        max(rows["off"]["decode_tok_s"], 1e-9)
    legs["spec"] = {m: {k: v for k, v in r.items() if k != "handles"}
                    for m, r in rows.items()}
    legs["spec"]["decode_speedup"] = round(speedup, 2)
    legs["spec"]["byte_identical"] = True
    gate_rows.append({
        "metric": "serve spec decode speedup (n-gram-friendly "
                  "workload, off->on)",
        "value": round(speedup, 3), "unit": "x",
        "higher_is_better": True, "label": label})

    legs["gate_rows"] = gate_rows
    return legs


# Forward GFLOPs are the standard published numbers (torchvision/tf-slim).
# family -> (module, init/loss kwargs, fwd GFLOP/img, canonical size,
# cpu-smoke size, sgd lr).  VGG's BN-less classifier diverges at the
# resnet-calibrated 0.1 (the original paper trained at 0.01).
CNN_FAMILIES = {
    "resnet50":   ("resnet", {"depth": 50}, 4.089e9, 224, 64, 0.1),
    "resnet101":  ("resnet", {"depth": 101}, 7.80e9, 224, 64, 0.1),
    "vgg16":      ("vgg", {"depth": 16}, 15.47e9, 224, 64, 0.01),
    "inception3": ("inception", {}, 5.73e9, 299, 139, 0.1),
}


def resnet_bench(args) -> int:
    """CNN synthetic images/sec — the reference's headline metric family
    (docs/benchmarks.rst:12-43: Inception V3 / ResNet-101 / VGG-16
    scaling rows; the img/sec table's `--model resnet101`, 1656.82 img/s
    over 16 Pascal GPUs ≈ 103.6 img/s/GPU, batch-64 synthetic protocol —
    matched exactly by ``--cnn resnet101``; ``--resnet --depth N`` is the
    back-compat spelling).

    Data-parallel over the whole mesh: per-chip batch shards, gradient
    pmean + cross-chip sync-BN statistics inside the scanned program, so
    images/sec/chip measures real scaled throughput."""
    import functools
    import importlib

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.ops._compat import shard_map
    from horovod_tpu.parallel.data_parallel import replicate, shard_batch

    family = args.cnn or f"resnet{args.depth}"
    mod_name, loss_kw, fwd_gflop, canonical_hw, cpu_hw, lr = \
        CNN_FAMILIES[family]
    model = importlib.import_module(f"horovod_tpu.models.{mod_name}")
    model_loss = functools.partial(model.loss_fn, **loss_kw)

    _init_with_retry(hvd, expect_tpu=not args.cpu)
    mesh = hvd.mesh()
    n_chips = hvd.size()
    default_batch = 32 if family == "vgg16" else 64  # VGG: 138M params
    batch = args.batch if args.batch is not None else default_batch
    steps = args.steps
    if args.cpu:
        batch, steps = 2, 3

    dtype = jnp.float32 if args.cpu else jnp.bfloat16
    params = replicate(model.init(jax.random.PRNGKey(0), dtype=dtype,
                                  **loss_kw), mesh)
    opt = optax.sgd(lr, momentum=0.9)
    opt_state = replicate(opt.init(params), mesh)

    rng = np.random.RandomState(0)
    size_hw = cpu_hw if args.cpu else canonical_hw
    x = shard_batch(jnp.asarray(
        rng.randn(batch * n_chips, size_hw, size_hw, 3), dtype), mesh)
    y = shard_batch(jnp.asarray(
        rng.randint(0, 1000, (batch * n_chips,)), jnp.int32), mesh)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), P(), P("hvd"), P("hvd")),
                       out_specs=(P(), P(), P()), check_vma=False)
    def run(params, opt_state, x, y):
        def one_step(carry, _):
            params, opt_state = carry
            (loss, new_params), g = jax.value_and_grad(
                model_loss, has_aux=True)(params, x, y,
                                          axis_name="hvd")
            g = jax.lax.pmean(g, "hvd")
            updates, opt_state = opt.update(g, opt_state)
            # new_params carries the BN running stats the forward
            # produced (already cross-chip via axis_name); gradient
            # updates for those leaves are zero, so applying on top
            # keeps both effects.
            params = optax.apply_updates(new_params, updates)
            return (params, opt_state), jax.lax.pmean(loss, "hvd")
        (params, opt_state), losses = jax.lax.scan(
            one_step, (params, opt_state), None, length=steps)
        return params, opt_state, losses

    params, opt_state, warm = run(params, opt_state, x, y)
    warm = np.asarray(warm)  # D2H fence
    if not np.all(np.isfinite(warm)):
        return fail("non-finite warmup loss", cause="invalid-result",
                    losses=warm.tolist())

    with maybe_profile(args):
        t0 = time.perf_counter()
        params, opt_state, losses = run(params, opt_state, x, y)
        losses_host = np.asarray(losses)
        dt = time.perf_counter() - t0

    if not np.all(np.isfinite(losses_host)):
        return fail("non-finite loss", cause="invalid-result",
                    losses=losses_host.tolist())
    # Params-not-updating shows as a constant loss WITHIN each scan; a
    # constant timed scan alone can be legitimate saturation (the tiny
    # cpu smoke memorizes its fixed batch to exactly 0.0 during warmup,
    # so the warm scan still shows movement).  Both scans internally
    # flat — even at different levels — means no training happened
    # inside the scans.
    if steps > 1 and float(np.ptp(losses_host)) == 0.0 and \
            float(np.ptp(warm)) == 0.0:
        return fail("loss constant across steps — params not updating",
                    cause="invalid-result",
                    losses=losses_host.tolist(), warmup=warm.tolist())

    # batch is PER CHIP: global throughput / n_chips == steps*batch/dt.
    img_per_sec_chip = steps * batch / dt
    chip = detect_chip()
    peak = _costmodel().peak_flops(chip)
    scale_flops = (size_hw / canonical_hw) ** 2
    train_flops_per_img = 3.0 * fwd_gflop * scale_flops
    mfu = img_per_sec_chip * train_flops_per_img / peak
    if not (0.0 < mfu < 1.0):
        return fail(f"MFU {mfu:.4f} outside (0,1)",
                    cause="invalid-result", chip=chip,
                    img_per_sec_chip=img_per_sec_chip)

    print(json.dumps({
        "metric": f"{family} train images/sec/chip ({chip}, "
                  f"batch={batch}, {size_hw}x{size_hw}, loss "
                  f"{float(losses_host[0]):.3f}->"
                  f"{float(losses_host[-1]):.3f})",
        "value": round(img_per_sec_chip, 1),
        "unit": "images/sec/chip",
        "mfu": round(mfu, 4),
        "vs_baseline_is": "mfu",
        "vs_baseline": round(mfu, 4),
        "metrics": metrics_summary(),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
